"""Generate the bootstrap knowledge base (docqa_tpu/default_data/*.csv).

The reference ships 649 denormalized TCM rows (`semantic-indexer/
default_data/`, consumed at `indexer.py:50-94`).  That content cannot be
copied, so this script AUTHORS an equivalent-scale knowledge base from the
structured tables below — classical formula compositions and syndrome/plant
affinities that are standard TCM curriculum material, written in this
file's own words and the repo's simplified column schemas:

* ``base_connaissance_tcm.csv`` — one row per (syndrome, formule, plante,
  role, score): the formula-composition view (reference
  ``indexer.py:79-89``).
* ``matrice_plante_syndrome.csv`` — one row per (syndrome, plante, score):
  the ranking-matrix view (reference ``indexer.py:67-76``).

Deterministic: re-running reproduces byte-identical CSVs.  Run from the
repo root: ``python scripts/gen_kb.py``.
"""

from __future__ import annotations

import csv
import os

OUT_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "docqa_tpu",
    "default_data",
)

# (latin, pinyin) — the herb lexicon used by both tables
PLANTS = {
    "ren_shen": ("Panax ginseng", "Ren Shen"),
    "huang_qi": ("Astragalus membranaceus", "Huang Qi"),
    "bai_zhu": ("Atractylodes macrocephala", "Bai Zhu"),
    "fu_ling": ("Poria cocos", "Fu Ling"),
    "gan_cao": ("Glycyrrhiza uralensis", "Gan Cao"),
    "dang_gui": ("Angelica sinensis", "Dang Gui"),
    "shu_di": ("Rehmannia glutinosa praeparata", "Shu Di Huang"),
    "bai_shao": ("Paeonia lactiflora", "Bai Shao"),
    "chuan_xiong": ("Ligusticum chuanxiong", "Chuan Xiong"),
    "chai_hu": ("Bupleurum chinense", "Chai Hu"),
    "bo_he": ("Mentha haplocalyx", "Bo He"),
    "sheng_jiang": ("Zingiber officinale recens", "Sheng Jiang"),
    "da_zao": ("Ziziphus jujuba", "Da Zao"),
    "chen_pi": ("Citrus reticulata", "Chen Pi"),
    "ban_xia": ("Pinellia ternata", "Ban Xia"),
    "shan_yao": ("Dioscorea opposita", "Shan Yao"),
    "shan_zhu_yu": ("Cornus officinalis", "Shan Zhu Yu"),
    "mu_dan_pi": ("Paeonia suffruticosa", "Mu Dan Pi"),
    "ze_xie": ("Alisma orientale", "Ze Xie"),
    "gou_qi": ("Lycium barbarum", "Gou Qi Zi"),
    "ju_hua": ("Chrysanthemum morifolium", "Ju Hua"),
    "jin_yin_hua": ("Lonicera japonica", "Jin Yin Hua"),
    "lian_qiao": ("Forsythia suspensa", "Lian Qiao"),
    "jie_geng": ("Platycodon grandiflorus", "Jie Geng"),
    "ma_huang": ("Ephedra sinica", "Ma Huang"),
    "gui_zhi": ("Cinnamomum cassia ramulus", "Gui Zhi"),
    "xing_ren": ("Prunus armeniaca semen", "Xing Ren"),
    "tao_ren": ("Prunus persica semen", "Tao Ren"),
    "hong_hua": ("Carthamus tinctorius", "Hong Hua"),
    "suan_zao_ren": ("Ziziphus spinosa semen", "Suan Zao Ren"),
    "yuan_zhi": ("Polygala tenuifolia", "Yuan Zhi"),
    "long_yan_rou": ("Dimocarpus longan arillus", "Long Yan Rou"),
    "mai_dong": ("Ophiopogon japonicus", "Mai Men Dong"),
    "wu_wei_zi": ("Schisandra chinensis", "Wu Wei Zi"),
    "huang_lian": ("Coptis chinensis", "Huang Lian"),
    "huang_qin": ("Scutellaria baicalensis", "Huang Qin"),
    "zhi_zi": ("Gardenia jasminoides", "Zhi Zi"),
    "da_huang": ("Rheum palmatum", "Da Huang"),
    "hou_po": ("Magnolia officinalis", "Hou Po"),
    "zhi_shi": ("Citrus aurantius immaturus", "Zhi Shi"),
    "sang_ye": ("Morus alba folium", "Sang Ye"),
    "ge_gen": ("Pueraria lobata", "Ge Gen"),
    "xi_xin": ("Asarum sieboldii", "Xi Xin"),
    "gan_jiang": ("Zingiber officinale siccatum", "Gan Jiang"),
    "rou_gui": ("Cinnamomum cassia cortex", "Rou Gui"),
    "du_zhong": ("Eucommia ulmoides", "Du Zhong"),
    "niu_xi": ("Achyranthes bidentata", "Niu Xi"),
    "sheng_ma": ("Cimicifuga foetida", "Sheng Ma"),
    "bai_he": ("Lilium brownii", "Bai He"),
    "zhi_mu": ("Anemarrhena asphodeloides", "Zhi Mu"),
    "shi_gao": ("Gypsum fibrosum", "Shi Gao"),
    "dan_shen": ("Salvia miltiorrhiza", "Dan Shen"),
    "xiang_fu": ("Cyperus rotundus", "Xiang Fu"),
    "mu_xiang": ("Aucklandia lappa", "Mu Xiang"),
    "sha_ren": ("Amomum villosum", "Sha Ren"),
    "yi_yi_ren": ("Coix lacryma-jobi", "Yi Yi Ren"),
    "zhe_bei_mu": ("Fritillaria thunbergii", "Zhe Bei Mu"),
    "gua_lou": ("Trichosanthes kirilowii", "Gua Lou"),
    "jing_jie": ("Schizonepeta tenuifolia", "Jing Jie"),
    "fang_feng": ("Saposhnikovia divaricata", "Fang Feng"),
    "qiang_huo": ("Notopterygium incisum", "Qiang Huo"),
    "du_huo": ("Angelica pubescens", "Du Huo"),
    "sang_ji_sheng": ("Taxillus chinensis", "Sang Ji Sheng"),
    "qin_jiao": ("Gentiana macrophylla", "Qin Jiao"),
    "zhu_ru": ("Phyllostachys nigra caulis", "Zhu Ru"),
    "shi_chang_pu": ("Acorus tatarinowii", "Shi Chang Pu"),
    "bai_zi_ren": ("Platycladus orientalis semen", "Bai Zi Ren"),
    "he_shou_wu": ("Polygonum multiflorum praeparata", "Zhi He Shou Wu"),
    "tu_si_zi": ("Cuscuta chinensis", "Tu Si Zi"),
    "yin_chen": ("Artemisia capillaris", "Yin Chen Hao"),
}

# key -> (nature, saveur, tropisme, indications, posologie,
#         contre_indications) — own-worded monograph prose (VERDICT r4
# item 8: the indexed sentences must carry quotable indication/posology/
# description text, not just scores; reference shape: the 34-column
# denormalized base the indexer templated at indexer.py:79-89).
MONOGRAPHS = {
    "ren_shen": (
        "tiède", "douce, légèrement amère", "Rate, Poumon, Coeur",
        "tonifie puissamment le Qi originel, soutient la Rate et le "
        "Poumon, engendre les liquides et calme l'esprit; fatigue "
        "profonde, essoufflement, palpitations avec épuisement",
        "3 à 9 g en décoction séparée; jusqu'à 15 g en cas "
        "d'effondrement du Qi",
        "éviter en cas de Chaleur pléthorique ou d'hypertension non "
        "contrôlée",
    ),
    "huang_qi": (
        "légèrement tiède", "douce", "Rate, Poumon",
        "tonifie le Qi et fait monter le Yang, consolide la surface et "
        "réduit les transpirations spontanées, favorise la "
        "cicatrisation; fatigue avec ptose, oedèmes par Vide de Qi",
        "9 à 30 g en décoction",
        "prudence en phase aiguë d'infection externe",
    ),
    "bai_zhu": (
        "tiède", "douce, amère", "Rate, Estomac",
        "renforce la Rate, assèche l'Humidité, stabilise la surface; "
        "appétit faible, selles molles, lassitude des membres",
        "6 à 12 g en décoction",
        "réserver en cas de Vide de Yin avec soif",
    ),
    "fu_ling": (
        "neutre", "douce, fade", "Coeur, Rate, Rein",
        "draine l'Humidité par la diurèse, renforce la Rate et apaise "
        "le Coeur; oedèmes, digestion lourde, sommeil agité",
        "9 à 15 g en décoction",
        "prudence en cas de polyurie avec Vide de Yin",
    ),
    "gan_cao": (
        "neutre", "douce", "les douze méridiens",
        "harmonise les autres plantes, tonifie le Qi du Foyer Moyen, "
        "humidifie le Poumon et calme les spasmes; toux, douleurs "
        "spasmodiques, harmonisation des formules",
        "2 à 6 g en décoction",
        "doses prolongées: rétention hydrosodée; éviter avec Gan Sui "
        "et Da Ji",
    ),
    "dang_gui": (
        "tiède", "douce, piquante", "Foie, Coeur, Rate",
        "nourrit le Sang et l'anime, régularise les menstruations, "
        "humidifie les intestins; teint pâle, règles irrégulières, "
        "constipation sèche du Vide de Sang",
        "6 à 12 g en décoction",
        "éviter en cas de diarrhée par Humidité de la Rate",
    ),
    "shu_di": (
        "légèrement tiède", "douce", "Foie, Rein",
        "nourrit en profondeur le Sang et le Yin, renforce l'Essence "
        "et la moelle; vertiges, acouphènes, lombes faibles, cheveux "
        "ternes",
        "9 à 15 g en décoction",
        "digestion faible: associer une plante qui mobilise (Sha Ren, "
        "Chen Pi)",
    ),
    "bai_shao": (
        "légèrement froide", "amère, acide", "Foie, Rate",
        "nourrit le Sang, assouplit le Foie, retient le Yin et calme "
        "la douleur; crampes, douleurs hypochondriaques, "
        "transpirations du Vide",
        "6 à 15 g en décoction",
        "incompatible avec Li Lu; prudence en cas de Froid-Vide",
    ),
    "chuan_xiong": (
        "tiède", "piquante", "Foie, Vésicule Biliaire, Péricarde",
        "anime le Sang et fait circuler le Qi, chasse le Vent et "
        "arrête la douleur; céphalées, règles douloureuses, douleurs "
        "par Stase",
        "3 à 9 g en décoction",
        "éviter en cas de Vide de Yin avec Feu ou de saignement actif",
    ),
    "chai_hu": (
        "légèrement froide", "amère, piquante",
        "Foie, Vésicule Biliaire",
        "libère le Shao Yang, draine le Foie et fait monter le Qi "
        "clair; alternance froid-chaleur, oppression des flancs, "
        "humeur nouée",
        "3 à 9 g en décoction",
        "prudence en cas de montée du Yang du Foie ou de Vide de Yin",
    ),
    "bo_he": (
        "fraîche", "piquante", "Poumon, Foie",
        "disperse le Vent-Chaleur, clarifie la tête et la gorge, "
        "libère la surface; fièvre légère, gorge irritée, yeux rouges",
        "3 à 6 g, ajouté en fin de décoction",
        "transpirations abondantes du Vide: éviter",
    ),
    "sheng_jiang": (
        "tiède", "piquante", "Poumon, Rate, Estomac",
        "libère la surface du Vent-Froid, réchauffe l'Estomac et "
        "arrête les nausées; rhume débutant, vomissements par Froid",
        "3 à 9 g en décoction",
        "Chaleur interne ou Vide de Yin avec chaleur: réserver",
    ),
    "da_zao": (
        "tiède", "douce", "Rate, Estomac",
        "tonifie le Qi du Foyer Moyen, nourrit le Sang et adoucit les "
        "formules; fatigue digestive, nervosité par Vide de Sang",
        "3 à 10 fruits en décoction",
        "ballonnements par Humidité: limiter",
    ),
    "chen_pi": (
        "tiède", "piquante, amère", "Rate, Poumon",
        "fait circuler le Qi, assèche l'Humidité et transforme les "
        "Glaires; ballonnements, nausées, toux grasse",
        "3 à 9 g en décoction",
        "toux sèche par Vide de Yin: éviter",
    ),
    "ban_xia": (
        "tiède", "piquante", "Rate, Estomac, Poumon",
        "assèche l'Humidité et transforme les Glaires, fait descendre "
        "le Qi rebelle; nausées, vomissements, toux à expectoration "
        "abondante",
        "3 à 9 g (préparée) en décoction",
        "toujours utiliser la forme préparée; prudence pendant la "
        "grossesse",
    ),
    "shan_yao": (
        "neutre", "douce", "Rate, Poumon, Rein",
        "tonifie doucement la Rate, le Poumon et le Rein, retient "
        "l'Essence; selles molles chroniques, toux faible, leucorrhées",
        "9 à 30 g en décoction",
        "peu de restrictions; stagnation avec plénitude: limiter",
    ),
    "shan_zhu_yu": (
        "légèrement tiède", "acide, astringente", "Foie, Rein",
        "retient l'Essence et les liquides, tonifie le Foie et le "
        "Rein; transpirations profuses, pollakiurie, vertiges",
        "6 à 12 g en décoction",
        "dysurie par Chaleur-Humidité: éviter",
    ),
    "mu_dan_pi": (
        "légèrement froide", "amère, piquante", "Coeur, Foie, Rein",
        "rafraîchit le Sang sans figer, anime le Sang et clarifie la "
        "Chaleur-Vide; fièvres vespérales, règles en avance",
        "6 à 12 g en décoction",
        "grossesse et règles abondantes: prudence",
    ),
    "ze_xie": (
        "froide", "douce, fade", "Rein, Vessie",
        "draine l'Humidité et clarifie la Chaleur du Foyer Inférieur; "
        "oedèmes, urines rares, vertiges par Glaires-Humidité",
        "6 à 12 g en décoction",
        "Vide de Yang avec polyurie claire: éviter",
    ),
    "gou_qi": (
        "neutre", "douce", "Foie, Rein, Poumon",
        "nourrit le Yin du Foie et du Rein, éclaire les yeux, "
        "humidifie le Poumon; vision floue, lombes douloureuses, soif",
        "6 à 12 g en décoction ou tel quel",
        "diarrhée par Vide de Rate: limiter",
    ),
    "ju_hua": (
        "légèrement froide", "douce, amère", "Poumon, Foie",
        "disperse le Vent-Chaleur, apaise le Foie et éclaire les "
        "yeux; céphalées, yeux rouges et secs, vertiges légers",
        "5 à 10 g en infusion ou décoction courte",
        "diarrhée par Froid-Vide: prudence",
    ),
    "jin_yin_hua": (
        "froide", "douce", "Poumon, Coeur, Estomac",
        "clarifie la Chaleur et élimine la Toxicité, disperse le "
        "Vent-Chaleur; angines, furoncles, fièvre des affections "
        "externes",
        "6 à 15 g en décoction",
        "plaies froides et Vide de la Rate: éviter",
    ),
    "lian_qiao": (
        "légèrement froide", "amère", "Poumon, Coeur, Vésicule Biliaire",
        "clarifie la Chaleur, élimine la Toxicité et disperse les "
        "nouures; fièvre, gorge enflée, abcès débutants",
        "6 à 15 g en décoction",
        "diarrhée par Vide de Rate: prudence",
    ),
    "jie_geng": (
        "neutre", "amère, piquante", "Poumon",
        "ouvre le Poumon, transforme les Glaires et porte l'action "
        "des formules vers le haut; toux, gorge enrouée, expectoration "
        "difficile",
        "3 à 9 g en décoction",
        "toux sèche par montée du Qi avec hémoptysie: éviter",
    ),
    "ma_huang": (
        "tiède", "piquante, légèrement amère", "Poumon, Vessie",
        "libère fortement la surface, fait transpirer, ouvre le "
        "Poumon et calme l'asthme; rhume sans transpiration, dyspnée",
        "2 à 9 g en décoction",
        "hypertension, palpitations, transpiration spontanée: éviter",
    ),
    "gui_zhi": (
        "tiède", "piquante, douce", "Coeur, Poumon, Vessie",
        "libère la surface et harmonise le Ying et le Wei, réchauffe "
        "les méridiens et soutient le Yang; rhume avec transpiration, "
        "membres froids, palpitations",
        "3 à 9 g en décoction",
        "maladies fébriles avec Chaleur, grossesse: prudence",
    ),
    "xing_ren": (
        "légèrement tiède", "amère", "Poumon, Gros Intestin",
        "fait descendre le Qi du Poumon, calme la toux et humidifie "
        "les intestins; toux, dyspnée, constipation sèche",
        "3 à 9 g en décoction",
        "toux par Vide sans plénitude: prudence; amande légèrement "
        "toxique à forte dose",
    ),
    "tao_ren": (
        "neutre", "amère, douce", "Coeur, Foie, Gros Intestin",
        "anime le Sang et disperse la Stase, humidifie les "
        "intestins; douleurs fixes, règles retardées, constipation",
        "4 à 9 g en décoction",
        "grossesse: contre-indiqué",
    ),
    "hong_hua": (
        "tiède", "piquante", "Coeur, Foie",
        "anime le Sang, débloque les menstruations et arrête la "
        "douleur de Stase; aménorrhée, douleurs thoraciques fixes",
        "3 à 9 g en décoction",
        "grossesse et tendance hémorragique: contre-indiqué",
    ),
    "suan_zao_ren": (
        "neutre", "douce, acide", "Coeur, Foie, Vésicule Biliaire",
        "nourrit le Coeur et le Foie, calme l'esprit et retient les "
        "transpirations; insomnie, rêves abondants, palpitations",
        "9 à 15 g en décoction, légèrement torréfiée",
        "Chaleur pléthorique avec agitation: réserver",
    ),
    "yuan_zhi": (
        "légèrement tiède", "amère, piquante", "Coeur, Rein, Poumon",
        "relie le Coeur et le Rein, calme l'esprit et transforme les "
        "Glaires; insomnie avec anxiété, mémoire faible, toux grasse",
        "3 à 9 g en décoction",
        "gastrite ou ulcère: prudence",
    ),
    "long_yan_rou": (
        "tiède", "douce", "Coeur, Rate",
        "nourrit le Sang du Coeur et tonifie la Rate, apaise "
        "l'esprit; insomnie de surmenage, palpitations, mémoire faible",
        "9 à 15 g en décoction",
        "Glaires-Humidité ou stagnation digestive: limiter",
    ),
    "mai_dong": (
        "légèrement froide", "douce, légèrement amère",
        "Coeur, Poumon, Estomac",
        "nourrit le Yin du Poumon et de l'Estomac, engendre les "
        "liquides et apaise le Coeur; toux sèche, soif, agitation "
        "nocturne",
        "6 à 12 g en décoction",
        "toux grasse par Froid ou diarrhée: éviter",
    ),
    "wu_wei_zi": (
        "tiède", "acide", "Poumon, Coeur, Rein",
        "retient le Qi du Poumon, consolide l'Essence et calme "
        "l'esprit; toux chronique, transpirations, diarrhée de l'aube",
        "2 à 6 g en décoction",
        "affection externe en cours ou Chaleur interne: éviter",
    ),
    "huang_lian": (
        "froide", "amère", "Coeur, Rate, Estomac, Gros Intestin",
        "clarifie la Chaleur et assèche l'Humidité, draine le Feu et "
        "élimine la Toxicité; dysenterie, agitation avec insomnie, "
        "aphtes",
        "2 à 5 g en décoction",
        "très amère et froide: Vide de Rate sans Chaleur, éviter",
    ),
    "huang_qin": (
        "froide", "amère", "Poumon, Vésicule Biliaire, Estomac, "
        "Gros Intestin",
        "clarifie la Chaleur du Foyer Supérieur, assèche l'Humidité "
        "et calme le foetus; toux jaune, fièvre persistante, diarrhée "
        "chaude",
        "3 à 9 g en décoction",
        "Froid-Vide de la Rate: éviter",
    ),
    "zhi_zi": (
        "froide", "amère", "Coeur, Poumon, Triple Foyer",
        "draine le Feu des trois Foyers, élimine l'irritabilité et "
        "favorise la diurèse; insomnie fébrile, ictère, urines "
        "foncées",
        "6 à 9 g en décoction",
        "selles molles par Froid-Vide: éviter",
    ),
    "da_huang": (
        "froide", "amère", "Rate, Estomac, Gros Intestin, Foie, Coeur",
        "purge la Chaleur accumulée, anime le Sang et élimine la "
        "Toxicité; constipation par Chaleur, abdomen plein et "
        "douloureux",
        "3 à 12 g, ajouté en fin de décoction pour purger",
        "grossesse, allaitement, menstruation: contre-indiqué",
    ),
    "hou_po": (
        "tiède", "amère, piquante", "Rate, Estomac, Poumon, "
        "Gros Intestin",
        "fait circuler le Qi et dissout la plénitude, assèche "
        "l'Humidité et fait descendre le rebelle; ballonnement, "
        "oppression, toux chargée",
        "3 à 9 g en décoction",
        "grossesse: prudence; Vide de Qi sans stagnation: éviter",
    ),
    "zhi_shi": (
        "légèrement froide", "amère, piquante", "Rate, Estomac",
        "brise la stagnation du Qi et dissout les accumulations; "
        "plénitude épigastrique, constipation avec ballonnement",
        "3 à 9 g en décoction",
        "grossesse et Vide de Qi marqué: prudence",
    ),
    "sang_ye": (
        "froide", "douce, amère", "Poumon, Foie",
        "disperse le Vent-Chaleur, clarifie le Poumon et éclaire les "
        "yeux; toux sèche débutante, yeux rouges, céphalée légère",
        "5 à 9 g en décoction",
        "toux par Froid: réserver",
    ),
    "ge_gen": (
        "fraîche", "douce, piquante", "Rate, Estomac",
        "libère les muscles et fait monter le clair, engendre les "
        "liquides; nuque raide, fièvre sans transpiration franche, "
        "diarrhée chaude",
        "9 à 15 g en décoction",
        "transpirations profuses du Vide: prudence",
    ),
    "xi_xin": (
        "tiède", "piquante", "Poumon, Rein, Coeur",
        "chasse le Vent-Froid jusqu'aux os, réchauffe le Poumon et "
        "transforme les Glaires froides; douleurs dentaires par "
        "Froid, rhinite claire",
        "1 à 3 g en décoction — petite dose impérative",
        "ne pas dépasser 3 g; Vide de Yin avec chaleur: "
        "contre-indiqué",
    ),
    "gan_jiang": (
        "chaude", "piquante", "Rate, Estomac, Coeur, Poumon",
        "réchauffe le Foyer Moyen et fait revenir le Yang, transforme "
        "les Glaires froides; douleurs abdominales par Froid, membres "
        "glacés, toux claire",
        "3 à 9 g en décoction",
        "grossesse, Chaleur interne ou Vide de Yin: éviter",
    ),
    "rou_gui": (
        "très chaude", "piquante, douce", "Rein, Rate, Coeur, Foie",
        "réchauffe et tonifie le Yang du Rein, ramène le Feu à sa "
        "source, débloque les méridiens; lombes et genoux froids, "
        "polyurie claire, douleurs par Froid profond",
        "1 à 4 g, en poudre ou ajouté en fin de décoction",
        "grossesse, Chaleur par Vide de Yin, saignements: "
        "contre-indiqué",
    ),
    "du_zhong": (
        "tiède", "douce", "Foie, Rein",
        "tonifie le Foie et le Rein, fortifie les os et les tendons, "
        "calme le foetus; lombalgies chroniques, genoux faibles, "
        "hypertension par Vide",
        "9 à 15 g en décoction",
        "Chaleur par Vide de Yin: prudence",
    ),
    "niu_xi": (
        "neutre", "amère, acide", "Foie, Rein",
        "anime le Sang vers le bas, fortifie lombes et genoux, "
        "conduit le Feu et le Sang vers le Foyer Inférieur; douleurs "
        "lombaires, règles retardées, gingivorragies par montée du Feu",
        "6 à 12 g en décoction",
        "grossesse et règles abondantes: contre-indiqué",
    ),
    "sheng_ma": (
        "légèrement froide", "douce, piquante",
        "Poumon, Rate, Estomac, Gros Intestin",
        "fait monter le Yang clair et élève ce qui s'effondre, "
        "élimine la Toxicité; ptoses, prolapsus, éruptions qui ne "
        "sortent pas",
        "3 à 9 g en décoction",
        "montée du Yang du Foie ou plénitude en haut: éviter",
    ),
    "bai_he": (
        "légèrement froide", "douce", "Coeur, Poumon",
        "humidifie le Poumon, calme la toux et apaise le Coeur; toux "
        "sèche persistante, agitation avec tristesse, insomnie "
        "post-fébrile",
        "6 à 12 g en décoction",
        "toux par Froid avec Glaires: éviter",
    ),
    "zhi_mu": (
        "froide", "amère, douce", "Poumon, Estomac, Rein",
        "clarifie la Chaleur et draine le Feu, nourrit le Yin et "
        "humidifie la sécheresse; fièvre élevée avec soif, chaleur "
        "des cinq coeurs, toux sèche",
        "6 à 12 g en décoction",
        "diarrhée par Froid-Vide de la Rate: éviter",
    ),
    "shi_gao": (
        "très froide", "douce, piquante", "Poumon, Estomac",
        "clarifie puissamment la Chaleur du niveau Qi, draine le Feu "
        "du Poumon et de l'Estomac; forte fièvre avec soif et "
        "transpiration, toux brûlante, gencives enflées",
        "15 à 60 g, concassé, décoction prolongée",
        "Froid-Vide de la Rate et de l'Estomac: contre-indiqué",
    ),
    "dan_shen": (
        "légèrement froide", "amère", "Coeur, Péricarde, Foie",
        "anime le Sang et disperse la Stase, rafraîchit le Sang et "
        "apaise l'esprit; douleurs thoraciques, règles douloureuses, "
        "insomnie avec agitation",
        "6 à 15 g en décoction",
        "incompatible avec Li Lu; prudence sous anticoagulants",
    ),
    "xiang_fu": (
        "neutre", "piquante, légèrement amère et douce",
        "Foie, Triple Foyer",
        "fait circuler le Qi du Foie et régularise les "
        "menstruations; humeur nouée, douleurs des flancs, règles "
        "irrégulières par stagnation",
        "6 à 12 g en décoction",
        "Vide de Qi sans stagnation ou Vide de Yin avec chaleur: "
        "prudence",
    ),
    "mu_xiang": (
        "tiède", "piquante, amère", "Rate, Estomac, Gros Intestin, "
        "Vésicule Biliaire",
        "fait circuler le Qi et arrête la douleur digestive, réveille "
        "la Rate; ballonnements douloureux, ténesme, appétit bloqué",
        "3 à 9 g, ajouté en fin de décoction",
        "Vide de Yin avec sécheresse: prudence",
    ),
    "sha_ren": (
        "tiède", "piquante", "Rate, Estomac, Rein",
        "mobilise le Qi, réveille la Rate, transforme l'Humidité et "
        "calme le foetus; digestion lourde, nausées matinales, "
        "diarrhée par Froid-Humidité",
        "3 à 6 g, ajouté en fin de décoction",
        "Chaleur par Vide de Yin: prudence",
    ),
    "yi_yi_ren": (
        "légèrement froide", "douce, fade", "Rate, Estomac, Poumon",
        "draine l'Humidité en douceur, renforce la Rate, clarifie la "
        "Chaleur et évacue le pus; oedèmes, courbatures par Humidité, "
        "diarrhée",
        "9 à 30 g en décoction",
        "grossesse: prudence",
    ),
    "zhe_bei_mu": (
        "froide", "amère", "Poumon, Coeur",
        "transforme les Glaires-Chaleur, dissout les nouures et "
        "arrête la toux; toux jaune et épaisse, gorge enflée, "
        "nodules",
        "4 à 9 g en décoction",
        "incompatible avec les Aconits; toux froide: réserver",
    ),
    "gua_lou": (
        "froide", "douce", "Poumon, Estomac, Gros Intestin",
        "transforme les Glaires-Chaleur, ouvre la poitrine et "
        "humidifie les intestins; oppression thoracique, toux "
        "grasse jaune, constipation sèche",
        "9 à 15 g en décoction",
        "incompatible avec les Aconits; diarrhée par Vide: éviter",
    ),
    "jing_jie": (
        "légèrement tiède", "piquante", "Poumon, Foie",
        "libère la surface et chasse le Vent, favorise l'éruption; "
        "rhume qu'il soit Froid ou Chaleur, urticaire, début "
        "d'éruption",
        "4 à 9 g en décoction courte",
        "éruption déjà complètement sortie: inutile",
    ),
    "fang_feng": (
        "légèrement tiède", "piquante, douce", "Vessie, Foie, Rate",
        "chasse le Vent de la surface et des articulations, vainc "
        "l'Humidité et arrête les spasmes; courbatures fébriles, "
        "démangeaisons, raideurs",
        "4 à 9 g en décoction",
        "spasmes par Vide de Sang sans Vent externe: réserver",
    ),
    "qiang_huo": (
        "tiède", "piquante, amère", "Vessie, Rein",
        "chasse le Vent-Froid-Humidité du haut du corps, libère la "
        "surface; nuque et épaules douloureuses, céphalée occipitale",
        "3 à 9 g en décoction",
        "douleurs par Vide de Sang: éviter; arôme puissant, nausées "
        "possibles",
    ),
    "du_huo": (
        "tiède", "piquante, amère", "Rein, Vessie",
        "chasse le Vent-Froid-Humidité du bas du corps; lombalgies et "
        "douleurs des genoux aggravées au froid, sciatique",
        "3 à 9 g en décoction",
        "douleurs par Chaleur ou Vide de Yin: réserver",
    ),
    "sang_ji_sheng": (
        "neutre", "amère, douce", "Foie, Rein",
        "tonifie le Foie et le Rein, fortifie tendons et os, chasse "
        "le Vent-Humidité et calme le foetus; lombalgies chroniques, "
        "articulations faibles, grossesse agitée",
        "9 à 15 g en décoction",
        "peu de restrictions connues",
    ),
    "qin_jiao": (
        "neutre", "amère, piquante", "Foie, Vésicule Biliaire, Estomac",
        "chasse le Vent-Humidité sans assécher, détend les tendons et "
        "clarifie la Chaleur-Vide; douleurs articulaires errantes, "
        "fièvre vespérale chronique",
        "4 à 9 g en décoction",
        "diarrhée par Vide de Rate: prudence",
    ),
    "zhu_ru": (
        "légèrement froide", "douce", "Poumon, Estomac, Vésicule "
        "Biliaire",
        "clarifie la Chaleur et transforme les Glaires, arrête les "
        "nausées; vomissements amers, toux jaune, agitation avec "
        "insomnie",
        "4 à 9 g en décoction",
        "vomissements par Froid d'Estomac: éviter",
    ),
    "shi_chang_pu": (
        "tiède", "piquante, amère", "Coeur, Estomac",
        "ouvre les orifices et transforme les Glaires, réveille "
        "l'esprit et la Rate; confusion par Glaires, mémoire faible, "
        "poitrine oppressée",
        "3 à 9 g en décoction",
        "Vide de Yin avec agitation du Yang: prudence",
    ),
    "bai_zi_ren": (
        "neutre", "douce", "Coeur, Rein, Gros Intestin",
        "nourrit le Coeur et calme l'esprit, humidifie les "
        "intestins; insomnie avec palpitations, transpirations "
        "nocturnes, constipation des personnes âgées",
        "9 à 15 g en décoction",
        "selles molles ou Glaires abondantes: éviter",
    ),
    "he_shou_wu": (
        "légèrement tiède", "douce, amère, astringente", "Foie, Rein",
        "nourrit le Sang et l'Essence sans figer, noircit les "
        "cheveux, fortifie os et tendons; cheveux blancs précoces, "
        "vertiges, lombes faibles",
        "9 à 15 g en décoction (forme préparée)",
        "utiliser la forme préparée; surveiller la fonction "
        "hépatique en usage prolongé",
    ),
    "tu_si_zi": (
        "neutre", "piquante, douce", "Foie, Rein, Rate",
        "tonifie le Yang sans assécher et nourrit le Yin, retient "
        "l'Essence et éclaire les yeux; lombes faibles, urines "
        "fréquentes, vision baissée",
        "6 à 12 g en décoction",
        "Chaleur par Vide de Yin avec constipation: prudence",
    ),
    "yin_chen": (
        "légèrement froide", "amère, piquante", "Rate, Estomac, Foie, "
        "Vésicule Biliaire",
        "clarifie la Chaleur-Humidité et fait disparaître l'ictère; "
        "jaunisse, urines foncées, sensation de lourdeur",
        "6 à 15 g en décoction",
        "ictère par Froid-Vide: associer des plantes qui réchauffent",
    ),
}

# formula -> (syndrome, [(plant_key, role, score), ...])
# Roles follow the classical hierarchy: Empereur / Ministre / Assistant /
# Messager.  Scores (1-10) rank the herb's weight within the formula.
FORMULAS = {
    "Si Jun Zi Tang": (
        "Vide de Qi de la Rate",
        [
            ("ren_shen", "Empereur", 9),
            ("bai_zhu", "Ministre", 7),
            ("fu_ling", "Assistant", 6),
            ("gan_cao", "Messager", 4),
        ],
    ),
    "Bu Zhong Yi Qi Tang": (
        "Effondrement du Qi central",
        [
            ("huang_qi", "Empereur", 9),
            ("ren_shen", "Ministre", 8),
            ("bai_zhu", "Ministre", 6),
            ("dang_gui", "Assistant", 5),
            ("chen_pi", "Assistant", 4),
            ("sheng_ma", "Messager", 3),
            ("chai_hu", "Messager", 3),
            ("gan_cao", "Messager", 3),
        ],
    ),
    "Si Wu Tang": (
        "Vide de Sang",
        [
            ("shu_di", "Empereur", 9),
            ("dang_gui", "Ministre", 8),
            ("bai_shao", "Assistant", 6),
            ("chuan_xiong", "Messager", 5),
        ],
    ),
    "Tao Hong Si Wu Tang": (
        "Stase de Sang",
        [
            ("tao_ren", "Empereur", 8),
            ("hong_hua", "Empereur", 8),
            ("shu_di", "Ministre", 6),
            ("dang_gui", "Ministre", 6),
            ("bai_shao", "Assistant", 5),
            ("chuan_xiong", "Assistant", 5),
        ],
    ),
    "Xiao Yao San": (
        "Stagnation du Qi du Foie",
        [
            ("chai_hu", "Empereur", 9),
            ("dang_gui", "Ministre", 7),
            ("bai_shao", "Ministre", 7),
            ("bai_zhu", "Assistant", 5),
            ("fu_ling", "Assistant", 5),
            ("bo_he", "Messager", 3),
            ("sheng_jiang", "Messager", 2),
            ("gan_cao", "Messager", 3),
        ],
    ),
    "Liu Wei Di Huang Wan": (
        "Vide de Yin du Rein",
        [
            ("shu_di", "Empereur", 9),
            ("shan_zhu_yu", "Ministre", 7),
            ("shan_yao", "Ministre", 7),
            ("ze_xie", "Assistant", 5),
            ("mu_dan_pi", "Assistant", 5),
            ("fu_ling", "Assistant", 5),
        ],
    ),
    "Qi Ju Di Huang Wan": (
        "Vide de Yin du Foie et du Rein",
        [
            ("gou_qi", "Empereur", 8),
            ("ju_hua", "Empereur", 7),
            ("shu_di", "Ministre", 7),
            ("shan_zhu_yu", "Ministre", 6),
            ("shan_yao", "Assistant", 5),
            ("mu_dan_pi", "Assistant", 4),
        ],
    ),
    "Er Chen Tang": (
        "Mucosités-Humidité",
        [
            ("ban_xia", "Empereur", 9),
            ("chen_pi", "Ministre", 7),
            ("fu_ling", "Assistant", 6),
            ("gan_cao", "Messager", 3),
        ],
    ),
    "Yin Qiao San": (
        "Vent-Chaleur",
        [
            ("jin_yin_hua", "Empereur", 9),
            ("lian_qiao", "Empereur", 9),
            ("bo_he", "Ministre", 6),
            ("jie_geng", "Assistant", 5),
            ("gan_cao", "Messager", 3),
        ],
    ),
    "Ma Huang Tang": (
        "Vent-Froid",
        [
            ("ma_huang", "Empereur", 9),
            ("gui_zhi", "Ministre", 7),
            ("xing_ren", "Assistant", 6),
            ("gan_cao", "Messager", 3),
        ],
    ),
    "Gui Zhi Tang": (
        "Vent-Froid avec transpiration",
        [
            ("gui_zhi", "Empereur", 9),
            ("bai_shao", "Ministre", 8),
            ("sheng_jiang", "Assistant", 5),
            ("da_zao", "Assistant", 4),
            ("gan_cao", "Messager", 4),
        ],
    ),
    "Gui Pi Tang": (
        "Vide de Qi et de Sang du Coeur et de la Rate",
        [
            ("huang_qi", "Empereur", 8),
            ("long_yan_rou", "Empereur", 7),
            ("ren_shen", "Ministre", 7),
            ("bai_zhu", "Ministre", 6),
            ("dang_gui", "Assistant", 6),
            ("suan_zao_ren", "Assistant", 6),
            ("yuan_zhi", "Assistant", 5),
            ("fu_ling", "Assistant", 4),
            ("gan_cao", "Messager", 3),
        ],
    ),
    "Tian Wang Bu Xin Dan": (
        "Vide de Yin du Coeur avec agitation",
        [
            ("shu_di", "Empereur", 8),
            ("mai_dong", "Ministre", 7),
            ("suan_zao_ren", "Ministre", 7),
            ("wu_wei_zi", "Assistant", 5),
            ("dang_gui", "Assistant", 5),
            ("yuan_zhi", "Assistant", 4),
        ],
    ),
    "Huang Lian Jie Du Tang": (
        "Chaleur-Toxicité des trois Foyers",
        [
            ("huang_lian", "Empereur", 9),
            ("huang_qin", "Ministre", 8),
            ("zhi_zi", "Assistant", 6),
        ],
    ),
    "Da Cheng Qi Tang": (
        "Accumulation de Chaleur au Foyer Moyen",
        [
            ("da_huang", "Empereur", 9),
            ("hou_po", "Ministre", 7),
            ("zhi_shi", "Assistant", 6),
        ],
    ),
    "Sang Ju Yin": (
        "Vent-Chaleur avec toux",
        [
            ("sang_ye", "Empereur", 8),
            ("ju_hua", "Ministre", 7),
            ("xing_ren", "Assistant", 6),
            ("jie_geng", "Assistant", 5),
            ("bo_he", "Messager", 4),
            ("gan_cao", "Messager", 3),
        ],
    ),
    "Ge Gen Tang": (
        "Vent-Froid avec raideur de la nuque",
        [
            ("ge_gen", "Empereur", 9),
            ("ma_huang", "Ministre", 6),
            ("gui_zhi", "Ministre", 6),
            ("bai_shao", "Assistant", 5),
            ("sheng_jiang", "Messager", 3),
            ("da_zao", "Messager", 3),
        ],
    ),
    "Li Zhong Wan": (
        "Froid-Vide de la Rate et de l'Estomac",
        [
            ("gan_jiang", "Empereur", 9),
            ("ren_shen", "Ministre", 7),
            ("bai_zhu", "Assistant", 6),
            ("gan_cao", "Messager", 4),
        ],
    ),
    "Jin Gui Shen Qi Wan": (
        "Vide de Yang du Rein",
        [
            ("rou_gui", "Empereur", 8),
            ("shu_di", "Ministre", 7),
            ("shan_zhu_yu", "Ministre", 6),
            ("shan_yao", "Assistant", 5),
            ("ze_xie", "Assistant", 4),
            ("fu_ling", "Assistant", 4),
            ("mu_dan_pi", "Assistant", 4),
        ],
    ),
    "Du Huo Ji Sheng Tang (variante)": (
        "Vide du Foie et du Rein avec douleurs lombaires",
        [
            ("du_zhong", "Empereur", 8),
            ("niu_xi", "Ministre", 7),
            ("dang_gui", "Assistant", 6),
            ("bai_shao", "Assistant", 5),
            ("chuan_xiong", "Assistant", 4),
            ("rou_gui", "Messager", 4),
        ],
    ),
    "Bai He Gu Jin Tang (variante)": (
        "Sécheresse du Poumon par Vide de Yin",
        [
            ("bai_he", "Empereur", 8),
            ("mai_dong", "Ministre", 7),
            ("shu_di", "Ministre", 6),
            ("bai_shao", "Assistant", 5),
            ("jie_geng", "Messager", 4),
            ("gan_cao", "Messager", 3),
        ],
    ),
    "Zhi Bai Di Huang Wan": (
        "Chaleur-Vide par Vide de Yin",
        [
            ("zhi_mu", "Empereur", 8),
            ("shu_di", "Ministre", 7),
            ("shan_zhu_yu", "Assistant", 5),
            ("shan_yao", "Assistant", 5),
            ("ze_xie", "Assistant", 4),
            ("mu_dan_pi", "Assistant", 4),
        ],
    ),
    "Xiao Chai Hu Tang": (
        "Syndrome Shao Yang",
        [
            ("chai_hu", "Empereur", 9),
            ("huang_qin", "Ministre", 7),
            ("ban_xia", "Assistant", 6),
            ("ren_shen", "Assistant", 5),
            ("sheng_jiang", "Messager", 3),
            ("da_zao", "Messager", 3),
            ("gan_cao", "Messager", 3),
        ],
    ),
    "Ping Wei San": (
        "Humidité obstruant le Foyer Moyen",
        [
            ("hou_po", "Empereur", 7),
            ("chen_pi", "Ministre", 6),
            ("bai_zhu", "Ministre", 6),
            ("gan_cao", "Messager", 3),
        ],
    ),
    "Suan Zao Ren Tang": (
        "Insomnie par Vide de Sang du Foie",
        [
            ("suan_zao_ren", "Empereur", 9),
            ("chuan_xiong", "Ministre", 5),
            ("fu_ling", "Assistant", 5),
            ("zhi_mu", "Assistant", 5),
            ("gan_cao", "Messager", 3),
        ],
    ),
    "Sheng Mai San": (
        "Vide de Qi et de Yin du Poumon",
        [
            ("ren_shen", "Empereur", 8),
            ("mai_dong", "Ministre", 7),
            ("wu_wei_zi", "Assistant", 6),
        ],
    ),
    "Ba Zhen Tang": (
        "Vide de Qi et de Sang",
        [
            ("ren_shen", "Empereur", 8),
            ("shu_di", "Empereur", 7),
            ("bai_zhu", "Ministre", 6),
            ("dang_gui", "Ministre", 7),
            ("fu_ling", "Assistant", 5),
            ("bai_shao", "Assistant", 5),
            ("chuan_xiong", "Messager", 4),
            ("gan_cao", "Messager", 3),
        ],
    ),
    "Shi Quan Da Bu Tang": (
        "Vide de Qi et de Sang avec Froid",
        [
            ("huang_qi", "Empereur", 8),
            ("ren_shen", "Ministre", 7),
            ("shu_di", "Ministre", 7),
            ("dang_gui", "Ministre", 6),
            ("bai_zhu", "Assistant", 5),
            ("fu_ling", "Assistant", 5),
            ("bai_shao", "Assistant", 5),
            ("chuan_xiong", "Assistant", 4),
            ("rou_gui", "Messager", 5),
            ("gan_cao", "Messager", 3),
        ],
    ),
    "Dang Gui Bu Xue Tang": (
        "Vide de Sang par effondrement du Qi",
        [
            ("huang_qi", "Empereur", 9),
            ("dang_gui", "Ministre", 5),
        ],
    ),
    "Zhen Wu Tang (variante)": (
        "Vide de Yang avec Eau débordante",
        [
            ("rou_gui", "Empereur", 8),
            ("fu_ling", "Ministre", 7),
            ("bai_zhu", "Ministre", 6),
            ("bai_shao", "Assistant", 5),
            ("sheng_jiang", "Messager", 5),
        ],
    ),
    "Wu Ling San (variante)": (
        "Rétention d'Eau par trouble de la transformation",
        [
            ("ze_xie", "Empereur", 8),
            ("fu_ling", "Ministre", 6),
            ("bai_zhu", "Ministre", 6),
            ("yi_yi_ren", "Assistant", 5),
            ("gui_zhi", "Messager", 5),
        ],
    ),
    "Xiao Qing Long Tang": (
        "Vent-Froid externe avec Glaires-Froid interne",
        [
            ("ma_huang", "Empereur", 8),
            ("gui_zhi", "Empereur", 7),
            ("gan_jiang", "Ministre", 6),
            ("xi_xin", "Ministre", 5),
            ("ban_xia", "Assistant", 6),
            ("wu_wei_zi", "Assistant", 5),
            ("bai_shao", "Assistant", 5),
            ("gan_cao", "Messager", 3),
        ],
    ),
    "Ban Xia Xie Xin Tang": (
        "Nouure de l'épigastre mêlant Froid et Chaleur",
        [
            ("ban_xia", "Empereur", 8),
            ("huang_lian", "Ministre", 6),
            ("huang_qin", "Ministre", 6),
            ("gan_jiang", "Assistant", 5),
            ("ren_shen", "Assistant", 5),
            ("da_zao", "Messager", 3),
            ("gan_cao", "Messager", 3),
        ],
    ),
    "Chai Hu Shu Gan San": (
        "Stagnation du Qi du Foie avec douleur des flancs",
        [
            ("chai_hu", "Empereur", 8),
            ("xiang_fu", "Ministre", 7),
            ("chuan_xiong", "Ministre", 6),
            ("bai_shao", "Assistant", 6),
            ("chen_pi", "Assistant", 5),
            ("zhi_shi", "Assistant", 5),
            ("gan_cao", "Messager", 3),
        ],
    ),
    "Xue Fu Zhu Yu Tang (variante)": (
        "Stase de Sang dans la poitrine",
        [
            ("tao_ren", "Empereur", 8),
            ("hong_hua", "Empereur", 7),
            ("dan_shen", "Ministre", 6),
            ("dang_gui", "Ministre", 6),
            ("chuan_xiong", "Assistant", 5),
            ("bai_shao", "Assistant", 4),
            ("niu_xi", "Assistant", 5),
            ("chai_hu", "Messager", 4),
            ("jie_geng", "Messager", 4),
            ("gan_cao", "Messager", 3),
        ],
    ),
    "Dan Shen Yin (variante)": (
        "Douleur épigastrique par Stase et stagnation du Qi",
        [
            ("dan_shen", "Empereur", 9),
            ("sha_ren", "Ministre", 5),
            ("mu_xiang", "Assistant", 4),
        ],
    ),
    "Jing Fang Bai Du San (variante)": (
        "Vent-Froid-Humidité en surface",
        [
            ("jing_jie", "Empereur", 7),
            ("fang_feng", "Empereur", 7),
            ("qiang_huo", "Ministre", 6),
            ("du_huo", "Ministre", 6),
            ("chai_hu", "Assistant", 5),
            ("chuan_xiong", "Assistant", 4),
            ("jie_geng", "Assistant", 4),
            ("zhi_shi", "Assistant", 4),
            ("fu_ling", "Assistant", 4),
            ("gan_cao", "Messager", 3),
        ],
    ),
    "Qiang Huo Sheng Shi Tang (variante)": (
        "Vent-Humidité de la nuque et du dos",
        [
            ("qiang_huo", "Empereur", 8),
            ("du_huo", "Ministre", 7),
            ("fang_feng", "Assistant", 6),
            ("chuan_xiong", "Assistant", 4),
            ("gan_cao", "Messager", 3),
        ],
    ),
    "San Miao Wan (variante)": (
        "Chaleur-Humidité du Foyer Inférieur",
        [
            ("huang_lian", "Empereur", 7),
            ("yi_yi_ren", "Ministre", 6),
            ("niu_xi", "Assistant", 5),
        ],
    ),
    "Yin Chen Hao Tang": (
        "Ictère par Chaleur-Humidité",
        [
            ("yin_chen", "Empereur", 9),
            ("zhi_zi", "Ministre", 6),
            ("da_huang", "Assistant", 5),
        ],
    ),
    "Wen Dan Tang": (
        "Glaires-Chaleur troublant l'esprit",
        [
            ("ban_xia", "Empereur", 7),
            ("zhu_ru", "Empereur", 7),
            ("zhi_shi", "Ministre", 6),
            ("chen_pi", "Ministre", 5),
            ("fu_ling", "Assistant", 5),
            ("sheng_jiang", "Messager", 3),
            ("da_zao", "Messager", 2),
            ("gan_cao", "Messager", 3),
        ],
    ),
    "Qing Qi Hua Tan Wan (variante)": (
        "Toux par Glaires-Chaleur",
        [
            ("zhe_bei_mu", "Empereur", 7),
            ("gua_lou", "Empereur", 7),
            ("huang_qin", "Ministre", 6),
            ("ban_xia", "Ministre", 5),
            ("xing_ren", "Assistant", 5),
            ("chen_pi", "Assistant", 4),
            ("zhi_shi", "Assistant", 4),
            ("fu_ling", "Assistant", 4),
        ],
    ),
    "An Shen Ding Zhi Wan (variante)": (
        "Frayeur par Vide du Qi du Coeur",
        [
            ("ren_shen", "Empereur", 6),
            ("fu_ling", "Ministre", 6),
            ("shi_chang_pu", "Ministre", 6),
            ("yuan_zhi", "Assistant", 6),
            ("suan_zao_ren", "Assistant", 5),
        ],
    ),
    "Bai Zi Yang Xin Wan (variante)": (
        "Insomnie par Vide de Sang du Coeur",
        [
            ("bai_zi_ren", "Empereur", 8),
            ("suan_zao_ren", "Ministre", 6),
            ("dang_gui", "Ministre", 5),
            ("shu_di", "Assistant", 5),
            ("yuan_zhi", "Assistant", 5),
            ("mai_dong", "Assistant", 4),
        ],
    ),
    "Qi Bao Mei Ran Dan (variante)": (
        "Vide de l'Essence du Foie et du Rein",
        [
            ("he_shou_wu", "Empereur", 8),
            ("tu_si_zi", "Ministre", 6),
            ("gou_qi", "Ministre", 6),
            ("dang_gui", "Assistant", 5),
            ("niu_xi", "Messager", 4),
        ],
    ),
    "Ju Pi Zhu Ru Tang": (
        "Hoquet par Vide d'Estomac avec Chaleur",
        [
            ("chen_pi", "Empereur", 7),
            ("zhu_ru", "Empereur", 7),
            ("ren_shen", "Assistant", 4),
            ("sheng_jiang", "Assistant", 4),
            ("da_zao", "Messager", 2),
            ("gan_cao", "Messager", 3),
        ],
    ),
    "Xiang Sha Liu Jun Zi Tang": (
        "Vide de Qi de la Rate avec stagnation et Glaires",
        [
            ("ren_shen", "Empereur", 7),
            ("bai_zhu", "Ministre", 6),
            ("fu_ling", "Ministre", 6),
            ("ban_xia", "Assistant", 5),
            ("chen_pi", "Assistant", 5),
            ("mu_xiang", "Assistant", 5),
            ("sha_ren", "Assistant", 5),
            ("gan_cao", "Messager", 3),
        ],
    ),
    "Shen Ling Bai Zhu San (variante)": (
        "Vide de la Rate avec Humidité et diarrhée",
        [
            ("ren_shen", "Empereur", 7),
            ("fu_ling", "Ministre", 6),
            ("bai_zhu", "Ministre", 6),
            ("shan_yao", "Assistant", 6),
            ("yi_yi_ren", "Assistant", 5),
            ("sha_ren", "Assistant", 4),
            ("jie_geng", "Messager", 3),
            ("gan_cao", "Messager", 3),
        ],
    ),
    "Bai Hu Tang (variante)": (
        "Chaleur pléthorique du niveau Qi",
        [
            ("shi_gao", "Empereur", 9),
            ("zhi_mu", "Ministre", 7),
            ("gan_cao", "Messager", 3),
        ],
    ),
    "Ma Xing Shi Gan Tang": (
        "Chaleur du Poumon avec dyspnée",
        [
            ("ma_huang", "Empereur", 7),
            ("shi_gao", "Empereur", 8),
            ("xing_ren", "Ministre", 6),
            ("gan_cao", "Messager", 3),
        ],
    ),
    "Zhu Ye Shi Gao Tang (variante)": (
        "Chaleur résiduelle avec Vide de Qi et de Yin",
        [
            ("shi_gao", "Empereur", 8),
            ("mai_dong", "Ministre", 6),
            ("ban_xia", "Assistant", 5),
            ("ren_shen", "Assistant", 4),
            ("zhu_ru", "Assistant", 4),
            ("gan_cao", "Messager", 3),
        ],
    ),
}

# formula -> (indication prose, posologie prose) — own-worded usage text
# templated into every base row so a fresh-boot /ask can QUOTE indication
# and dosage, not just rankings (VERDICT r4 item 8).
FORMULA_INFO = {
    "Si Jun Zi Tang": (
        "la décoction des quatre gentilshommes traite la fatigue avec "
        "appétit faible, selles molles et voix sans force — le tableau "
        "du Vide de Qi de la Rate",
        "décoction quotidienne en deux prises tièdes, avant les repas",
    ),
    "Bu Zhong Yi Qi Tang": (
        "relève le Qi central effondré: lassitude aggravée à l'effort, "
        "ptoses d'organes, fièvre légère chronique du surmenage",
        "décoction en deux prises le matin et à midi; cure de plusieurs "
        "semaines",
    ),
    "Si Wu Tang": (
        "la décoction des quatre substances nourrit le Sang: teint et "
        "lèvres pâles, vertiges, règles peu abondantes ou retardées",
        "décoction quotidienne; en cure d'au moins un cycle menstruel",
    ),
    "Tao Hong Si Wu Tang": (
        "Si Wu Tang animée: règles douloureuses à caillots sombres, "
        "douleurs fixes par Stase sur fond de Vide de Sang",
        "décoction quotidienne pendant la période douloureuse",
    ),
    "Xiao Yao San": (
        "la poudre du vagabond insouciant dénoue le Foie et soutient la "
        "Rate: irritabilité, oppression des flancs, syndrome "
        "prémenstruel, appétit instable",
        "poudre 6 à 9 g deux fois par jour, ou décoction équivalente",
    ),
    "Liu Wei Di Huang Wan": (
        "la pilule aux six saveurs nourrit le Yin du Rein: vertiges, "
        "acouphènes, lombes faibles, transpirations nocturnes",
        "pilule 6 à 9 g deux fois par jour, en cure prolongée",
    ),
    "Qi Ju Di Huang Wan": (
        "Liu Wei augmentée pour les yeux: vision floue, yeux secs, "
        "éblouissements sur Vide de Yin du Foie et du Rein",
        "pilule 6 à 9 g deux fois par jour",
    ),
    "Er Chen Tang": (
        "la décoction des deux ingrédients mûris transforme les "
        "Glaires-Humidité: toux grasse blanche, nausées, langue à "
        "enduit gras",
        "décoction en deux prises après les repas",
    ),
    "Yin Qiao San": (
        "disperse le Vent-Chaleur naissant: fièvre avec mal de gorge, "
        "soif légère, début d'affection fébrile",
        "poudre ou décoction courte, toutes les 4 à 6 heures les deux "
        "premiers jours",
    ),
    "Ma Huang Tang": (
        "libère la surface fermée par le Vent-Froid: fièvre sans "
        "transpiration, frissons, courbatures, dyspnée",
        "décoction chaude; arrêter dès que la transpiration vient",
    ),
    "Gui Zhi Tang": (
        "harmonise Ying et Wei quand la surface reste ouverte: fièvre "
        "légère AVEC transpiration, aversion au vent",
        "décoction tiède suivie d'une bouillie chaude et de repos "
        "couvert",
    ),
    "Gui Pi Tang": (
        "restaure ensemble le Qi de la Rate et le Sang du Coeur: "
        "insomnie du surmenage intellectuel, palpitations, mémoire "
        "faible, règles abondantes et pâles",
        "décoction en deux prises, ou pilule 9 g matin et soir",
    ),
    "Tian Wang Bu Xin Dan": (
        "l'élixir du roi céleste nourrit le Yin du Coeur: insomnie "
        "avec agitation, bouche sèche nocturne, aphtes récidivants",
        "pilule 9 g au coucher, cure de plusieurs semaines",
    ),
    "Huang Lian Jie Du Tang": (
        "draine le Feu toxique des trois Foyers: fièvre intense avec "
        "agitation, dysenterie, furoncles, insomnie fébrile",
        "décoction courte; traitement bref, arrêter dès l'amélioration",
    ),
    "Da Cheng Qi Tang": (
        "purge majeure de la Chaleur liée: constipation opiniâtre, "
        "abdomen plein, douloureux au toucher, fièvre de plénitude",
        "décoction avec Da Huang ajouté en fin; usage ponctuel "
        "uniquement",
    ),
    "Sang Ju Yin": (
        "disperse le Vent-Chaleur léger avec toux: toux sèche "
        "débutante, fièvre discrète, gorge qui gratte",
        "décoction courte, deux à trois prises par jour",
    ),
    "Ge Gen Tang": (
        "libère la surface et les muscles de la nuque: rhume avec "
        "nuque et haut du dos raides, sans transpiration",
        "décoction chaude en deux prises",
    ),
    "Li Zhong Wan": (
        "réchauffe le Foyer Moyen glacé: douleurs abdominales "
        "améliorées par la chaleur, diarrhée claire, membres froids",
        "pilule 9 g ou décoction, deux à trois fois par jour",
    ),
    "Jin Gui Shen Qi Wan": (
        "la pilule du Qi du Rein réchauffe le Yang: lombes et genoux "
        "froids et faibles, polyurie claire nocturne, frilosité",
        "pilule 6 à 9 g deux fois par jour, en cure prolongée",
    ),
    "Du Huo Ji Sheng Tang (variante)": (
        "traite les lombalgies chroniques du Vide du Foie et du Rein "
        "avec Vent-Humidité: douleurs lombaires anciennes aggravées au "
        "froid, genoux faibles",
        "décoction quotidienne en cure de plusieurs semaines",
    ),
    "Bai He Gu Jin Tang (variante)": (
        "humidifie le Poumon désséché par le Vide de Yin: toux sèche "
        "persistante, gorge sèche, filets de sang dans l'expectoration",
        "décoction en deux prises, loin des repas",
    ),
    "Zhi Bai Di Huang Wan": (
        "Liu Wei renforcée contre la Chaleur-Vide: chaleur des cinq "
        "coeurs, transpirations nocturnes marquées, fièvre vespérale",
        "pilule 6 à 9 g deux fois par jour",
    ),
    "Xiao Chai Hu Tang": (
        "harmonise le Shao Yang: alternance de froid et de chaleur, "
        "bouche amère, nausées, oppression des flancs",
        "décoction en trois prises réparties dans la journée",
    ),
    "Ping Wei San": (
        "assèche l'Humidité qui encombre le Foyer Moyen: lourdeur "
        "épigastrique, langue à enduit épais et gras, goût fade",
        "poudre 3 à 6 g ou décoction, après les repas",
    ),
    "Suan Zao Ren Tang": (
        "nourrit le Foie et calme l'esprit: insomnie d'épuisement avec "
        "irritabilité, palpitations, gorge sèche nocturne",
        "décoction le soir, une heure avant le coucher",
    ),
    "Sheng Mai San": (
        "la poudre qui restaure le pouls: essoufflement avec "
        "transpiration et soif après maladie ou chaleur, voix faible",
        "décoction ou poudre, deux prises par jour",
    ),
    "Ba Zhen Tang": (
        "les huit trésors tonifient ensemble Qi et Sang: fatigue avec "
        "pâleur, vertiges, palpitations, convalescence",
        "décoction quotidienne en cure d'un mois",
    ),
    "Shi Quan Da Bu Tang": (
        "la grande tonification parfaite ajoute la chaleur: Vide de Qi "
        "et de Sang avec frilosité, plaies qui tardent à refermer",
        "décoction quotidienne ou pilule, en cure prolongée",
    ),
    "Dang Gui Bu Xue Tang": (
        "deux plantes seulement: le Qi massivement tonifié engendre le "
        "Sang — fièvre de Vide après hémorragie, fatigue du post-partum",
        "décoction quotidienne, cinq parts de Huang Qi pour une de "
        "Dang Gui",
    ),
    "Zhen Wu Tang (variante)": (
        "réchauffe le Yang pour maîtriser l'Eau: oedèmes avec membres "
        "lourds et froids, urines rares, vertiges",
        "décoction en deux prises tièdes",
    ),
    "Wu Ling San (variante)": (
        "restaure la transformation des liquides: oedèmes, urines "
        "rares, soif avec vomissement de l'eau bue",
        "poudre 6 g ou décoction, trois fois par jour",
    ),
    "Xiao Qing Long Tang": (
        "le petit dragon bleu disperse le Froid externe et les Glaires "
        "froides: toux à expectoration claire et abondante, dyspnée "
        "aggravée couché, rhinorrhée claire",
        "décoction chaude en deux prises",
    ),
    "Ban Xia Xie Xin Tang": (
        "dénoue l'épigastre où Froid et Chaleur se mêlent: plénitude "
        "sous le sternum sans douleur, nausées, borborygmes avec "
        "diarrhée",
        "décoction en deux prises entre les repas",
    ),
    "Chai Hu Shu Gan San": (
        "fait circuler le Qi du Foie noué: douleurs des flancs et de "
        "l'épigastre, soupirs, humeur sombre, règles irrégulières",
        "poudre 6 g ou décoction deux fois par jour",
    ),
    "Xue Fu Zhu Yu Tang (variante)": (
        "chasse la Stase du manoir du Sang: douleur thoracique fixe "
        "et piquante, céphalées anciennes, insomnie opiniâtre",
        "décoction quotidienne en cure courte renouvelable",
    ),
    "Dan Shen Yin (variante)": (
        "anime le Sang et mobilise le Qi à l'épigastre: douleur "
        "épigastrique ou thoracique fixe, aggravée la nuit",
        "décoction en deux prises",
    ),
    "Jing Fang Bai Du San (variante)": (
        "libère la surface du Vent-Froid-Humidité: frissons sans "
        "transpiration, courbatures lourdes, céphalée en casque",
        "décoction chaude dès les premiers frissons",
    ),
    "Qiang Huo Sheng Shi Tang (variante)": (
        "chasse le Vent-Humidité du haut du dos: nuque et épaules "
        "raides et douloureuses, lourdeur de la tête",
        "décoction en deux prises chaudes",
    ),
    "San Miao Wan (variante)": (
        "assèche la Chaleur-Humidité descendue: genoux chauds et "
        "gonflés, jambes lourdes, leucorrhées jaunes",
        "pilule 6 g deux fois par jour",
    ),
    "Yin Chen Hao Tang": (
        "fait disparaître l'ictère par Chaleur-Humidité: peau et yeux "
        "jaune vif, urines foncées, abdomen plein",
        "décoction quotidienne jusqu'à décoloration franche des urines",
    ),
    "Wen Dan Tang": (
        "réchauffe la Vésicule en clarifiant les Glaires: insomnie "
        "avec sursauts, vertiges, nausées, indécision anxieuse",
        "décoction en deux prises dont une au coucher",
    ),
    "Qing Qi Hua Tan Wan (variante)": (
        "clarifie le Qi et dissout les Glaires-Chaleur: toux à "
        "expectoration jaune et épaisse, oppression, visage rouge",
        "pilule 6 à 9 g deux fois par jour",
    ),
    "An Shen Ding Zhi Wan (variante)": (
        "stabilise l'esprit effrayé: sursauts au moindre bruit, "
        "sommeil peuplé de rêves, palpitations du Vide de Qi du Coeur",
        "pilule 9 g au coucher",
    ),
    "Bai Zi Yang Xin Wan (variante)": (
        "nourrit le Coeur par le Sang: insomnie avec palpitations et "
        "transpirations nocturnes, constipation sèche associée",
        "pilule 9 g le soir, cure de plusieurs semaines",
    ),
    "Qi Bao Mei Ran Dan (variante)": (
        "l'élixir des sept trésors nourrit l'Essence: cheveux blancs "
        "précoces, chute de cheveux, lombes faibles, vieillissement "
        "prématuré",
        "pilule 6 à 9 g deux fois par jour, cure longue",
    ),
    "Ju Pi Zhu Ru Tang": (
        "abaisse le Qi rebelle de l'Estomac affaibli: hoquet ou "
        "éructations persistantes après maladie, chaleur légère",
        "décoction en prises fractionnées dans la journée",
    ),
    "Xiang Sha Liu Jun Zi Tang": (
        "les six gentilshommes augmentés mobilisent ce que le Vide "
        "laisse stagner: digestion lente et douloureuse, ballonnement "
        "après les repas, nausées",
        "décoction en deux prises avant les repas",
    ),
    "Shen Ling Bai Zhu San (variante)": (
        "renforce la Rate et sèche la diarrhée chronique: selles "
        "molles récidivantes, fatigue, membres lourds",
        "poudre 6 g avec une bouillie de riz, deux fois par jour",
    ),
    "Bai Hu Tang (variante)": (
        "le tigre blanc éteint la Chaleur du niveau Qi: les quatre "
        "grands — grande fièvre, grande soif, grande transpiration, "
        "grand pouls",
        "décoction prolongée de gypse; réservée aux tableaux de "
        "plénitude",
    ),
    "Ma Xing Shi Gan Tang": (
        "clarifie le Poumon enflammé et calme le souffle: toux "
        "brûlante avec dyspnée, fièvre, soif, avec ou sans "
        "transpiration",
        "décoction en deux à trois prises",
    ),
    "Zhu Ye Shi Gao Tang (variante)": (
        "éteint la Chaleur résiduelle en soutenant les liquides: "
        "fièvre traînante après maladie, soif, langue rouge et sèche, "
        "nausées",
        "décoction tiède en trois prises",
    ),
}

# syndrome -> extra (plant, score) affinities beyond its formula's herbs —
# the ranking-matrix view covers single-herb indications too
EXTRA_AFFINITIES = {
    "Vide de Qi de la Rate": [
        ("huang_qi", 8),
        ("shan_yao", 6),
        ("da_zao", 5),
        ("gan_jiang", 4),
    ],
    "Vide de Sang": [
        ("long_yan_rou", 6),
        ("gou_qi", 6),
        ("da_zao", 5),
        ("suan_zao_ren", 4),
    ],
    "Stase de Sang": [("niu_xi", 6), ("mu_dan_pi", 5), ("da_huang", 4)],
    "Stagnation du Qi du Foie": [
        ("chen_pi", 5),
        ("zhi_shi", 5),
        ("bo_he", 4),
    ],
    "Vide de Yin du Rein": [
        ("gou_qi", 7),
        ("zhi_mu", 6),
        ("mai_dong", 5),
        ("bai_he", 4),
    ],
    "Vide de Yang du Rein": [("du_zhong", 7), ("gan_jiang", 5), ("niu_xi", 5)],
    "Mucosités-Humidité": [("hou_po", 6), ("zhi_shi", 5), ("jie_geng", 4)],
    "Vent-Chaleur": [("sang_ye", 7), ("ju_hua", 6), ("ge_gen", 5)],
    "Vent-Froid": [("sheng_jiang", 6), ("xi_xin", 6), ("ge_gen", 5)],
    "Chaleur-Toxicité des trois Foyers": [
        ("jin_yin_hua", 7),
        ("lian_qiao", 7),
        ("da_huang", 5),
    ],
    "Insomnie par Vide de Sang du Foie": [
        ("yuan_zhi", 6),
        ("long_yan_rou", 5),
        ("bai_he", 5),
    ],
    "Vide de Qi et de Yin du Poumon": [("huang_qi", 6), ("bai_he", 5)],
    "Sécheresse du Poumon par Vide de Yin": [
        ("sang_ye", 5),
        ("xing_ren", 4),
    ],
    "Chaleur-Vide par Vide de Yin": [("mai_dong", 5), ("bai_he", 4)],
    "Syndrome Shao Yang": [("huang_lian", 4), ("bo_he", 3)],
    "Vide de Yin du Coeur avec agitation": [
        ("bai_he", 6),
        ("long_yan_rou", 4),
    ],
    "Froid-Vide de la Rate et de l'Estomac": [
        ("rou_gui", 6),
        ("sheng_jiang", 5),
        ("da_zao", 4),
    ],
    "Humidité obstruant le Foyer Moyen": [("fu_ling", 6), ("ban_xia", 5)],
    "Effondrement du Qi central": [("shan_yao", 5), ("da_zao", 4)],
    "Vide de Yin du Foie et du Rein": [("bai_shao", 5), ("zhi_mu", 4)],
    "Accumulation de Chaleur au Foyer Moyen": [
        ("huang_lian", 5),
        ("zhi_zi", 4),
    ],
}


def write_base(path: str) -> int:
    """Denormalized (syndrome, formule, plante) rows WITH the monograph
    and formula prose — the columns a retrieval hit can quote (indication,
    posologie, contre-indications), mirroring the informational density of
    the reference's 34-column base (``indexer.py:79-89``) in this repo's
    own schema and words."""
    rows = 0
    with open(path, "w", newline="", encoding="utf-8") as f:
        w = csv.writer(f)
        w.writerow(
            [
                "nom_syndrome", "nom_formule", "nom_latin", "nom_chinois",
                "role", "score_role", "nature_plante", "saveur_plante",
                "tropisme_plante", "indications_plante", "posologie_plante",
                "contre_indications_plante", "indication_formule",
                "posologie_formule",
            ]
        )
        for formula, (syndrome, comp) in FORMULAS.items():
            f_ind, f_pos = FORMULA_INFO[formula]
            for key, role, score in comp:
                latin, pinyin = PLANTS[key]
                nature, saveur, trop, ind, pos, ci = MONOGRAPHS[key]
                w.writerow(
                    [
                        syndrome, formula, latin, pinyin, role, score,
                        nature, saveur, trop, ind, pos, ci, f_ind, f_pos,
                    ]
                )
                rows += 1
    return rows


def write_monographs(path: str) -> int:
    """One monograph row per herb: the single-plant reference view."""
    rows = 0
    with open(path, "w", newline="", encoding="utf-8") as f:
        w = csv.writer(f)
        w.writerow(
            [
                "nom_latin", "nom_chinois", "nature", "saveur", "tropisme",
                "indications", "posologie", "contre_indications",
            ]
        )
        for key, (latin, pinyin) in PLANTS.items():
            nature, saveur, trop, ind, pos, ci = MONOGRAPHS[key]
            w.writerow([latin, pinyin, nature, saveur, trop, ind, pos, ci])
            rows += 1
    return rows


def write_matrice(path: str) -> int:
    seen = set()
    rows = 0
    with open(path, "w", newline="", encoding="utf-8") as f:
        w = csv.writer(f)
        w.writerow(["nom_syndrome", "nom_latin", "nom_chinois", "score_role"])
        for formula, (syndrome, comp) in FORMULAS.items():
            for key, _role, score in comp:
                if (syndrome, key) in seen:
                    continue
                seen.add((syndrome, key))
                latin, pinyin = PLANTS[key]
                w.writerow([syndrome, latin, pinyin, score])
                rows += 1
        for syndrome, extras in EXTRA_AFFINITIES.items():
            for key, score in extras:
                if (syndrome, key) in seen:
                    continue
                seen.add((syndrome, key))
                latin, pinyin = PLANTS[key]
                w.writerow([syndrome, latin, pinyin, score])
                rows += 1
    return rows


def main() -> None:
    os.makedirs(OUT_DIR, exist_ok=True)
    n_base = write_base(os.path.join(OUT_DIR, "base_connaissance_tcm.csv"))
    n_mat = write_matrice(
        os.path.join(OUT_DIR, "matrice_plante_syndrome.csv")
    )
    n_mono = write_monographs(
        os.path.join(OUT_DIR, "monographies_plantes.csv")
    )
    print(
        f"wrote {n_base} base rows + {n_mat} matrice rows + {n_mono} "
        f"monograph rows = {n_base + n_mat + n_mono} total to {OUT_DIR}"
    )


if __name__ == "__main__":
    main()
