#!/usr/bin/env python3
"""docqa-numcheck CLI: drive the serving workloads, count compiles, and
hold compile counts + per-root HBM bytes to compile_budget.json.

Usage:
    python scripts/compile_audit.py                      # gate (exit 1 on
                                                         # drift)
    python scripts/compile_audit.py --report out.json    # also write the
                                                         # CI trend artifact
    python scripts/compile_audit.py --write-budget       # accept measured
                                                         # counts (HBM
                                                         # ceilings only
                                                         # grow through a
                                                         # TODO note the
                                                         # gate rejects
                                                         # until edited;
                                                         # jit-root reasons
                                                         # preserved)
    python scripts/compile_audit.py --workloads serve,generate

The gate fails on: any steady-state retrace, a compile count different
from the budget's, a root's measured peak bytes above its ceiling, a
TODO ceiling/waiver note, and a jit-root ledger out of sync with the
tree.  Runs on the CPU backend so CI and laptops measure the same
programs.  See docs/STATIC_ANALYSIS.md for the budget format and the
amendment workflow.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from docqa_tpu.analysis import compile_audit  # noqa: E402


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument(
        "--budget",
        default=None,
        help="budget JSON path (default: <repo>/compile_budget.json)",
    )
    parser.add_argument(
        "--report",
        default=None,
        help="write the measured report (counts + memory + roots) to this "
        "path (the CI compile/HBM trend artifact)",
    )
    parser.add_argument(
        "--write-budget",
        action="store_true",
        help="rewrite the budget from the measured counts (ceilings are "
        "preserved while the measurement fits; growth gets a TODO note "
        "the gate rejects until justified)",
    )
    parser.add_argument(
        "--workloads",
        default=None,
        help="comma-separated subset of: "
        + ", ".join(compile_audit.WORKLOADS),
    )
    args = parser.parse_args(argv)

    workloads = (
        [w.strip() for w in args.workloads.split(",") if w.strip()]
        if args.workloads
        else None
    )
    for name in workloads or ():
        if name not in compile_audit.WORKLOADS:
            parser.error(f"unknown workload '{name}'")

    report = compile_audit.run_audit(workloads=workloads)
    if args.report:
        with open(args.report, "w", encoding="utf-8") as f:
            json.dump(report, f, indent=2, sort_keys=True)
            f.write("\n")
        print(f"report -> {args.report}")

    if args.write_budget:
        if workloads:
            parser.error(
                "--write-budget needs a full run (no --workloads): a "
                "partial budget would be stale"
            )
        budget = compile_audit.write_budget(report, args.budget)
        todo = [
            f"{w}/{r}: {root['ceiling_note']}"
            for w, r, root in compile_audit._iter_roots(budget)
            if "TODO" in str(root.get("ceiling_note", ""))
        ]
        todo += [
            s for s, reason in budget["jit_roots"].items()
            if "TODO" in str(reason)
        ]
        print(
            f"budget updated -> "
            f"{args.budget or compile_audit.default_budget_path()}"
        )
        if todo:
            print(
                f"{len(todo)} entr(ies) need a human-written reason "
                f"before the gate passes:"
            )
            for s in todo:
                print(f"  {s}")
        return 0

    budget_path = args.budget or compile_audit.default_budget_path()
    if not os.path.exists(budget_path):
        print(
            f"no budget at {budget_path}; run --write-budget first",
            file=sys.stderr,
        )
        return 1
    budget = compile_audit.load_budget(budget_path)
    if workloads:
        # scoped runs compare only what they measured
        budget = dict(budget)
        budget["workloads"] = {
            k: v
            for k, v in budget.get("workloads", {}).items()
            if k in workloads
        }
        budget.pop("jit_roots", None)
        report = dict(report)
        report.pop("jit_roots", None)
    violations = compile_audit.compare_budget(report, budget)

    for wname, rname, root in sorted(compile_audit._iter_roots(report)):
        print(
            f"{wname:16s} {rname:18s} compiles={root.get('compiles')} "
            f"retraces={root.get('steady_state_retraces')} "
            f"peak={root.get('peak_bytes')}B"
        )
    if violations:
        print(f"\ncompile-audit: {len(violations)} violation(s):")
        for v in violations:
            print(f"  - {v}")
        return 1
    print("\ncompile-audit: budget satisfied")
    return 0


if __name__ == "__main__":
    sys.exit(main())
