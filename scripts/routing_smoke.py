#!/usr/bin/env python
"""Answer-routing smoke (docqa-lexroute; docs/OPERATIONS.md "Tune the
answer router") — the CI-blocking proof that the confidence-gated
router actually ships the decoder-skip fast path over the REAL wire.

A tiny-but-real runtime (real decoder + continuous batcher, hash-embed
fake encoder, lexical tier + router on their defaults) serves the
checked-in labeled query mix (``data/routing_mix.jsonl``, EN+FR,
20 extractive + 20 generative — authored like the deid HELDOUT set and
never tuned against) over real HTTP ``POST /ask/``.  The corpus is the
mix's own evidence docs, seeded through ``store.add`` so the lexical
sink indexes the raw text (the pipeline's deid stage would mask the
very MRN/phone tokens the lookups target — correct for PHI, wrong for
a routing measurement; the journal-replay ingest convergence has its
own regression test in ``tests/test_lexical.py``).

Blocking assertions, all structural (the only timing claim is the
route split's ORDERING, which the decoder-skip geometry forces):

1. routing precision >= 0.95 from the WIRE ``route`` key vs the mix's
   labels — an extractive-routed generative question ships a
   wrong-shaped answer, so precision is the hard floor;
2. enough extractive routes landed (>= 10 of 20) for the split to mean
   anything — an evidence-gate collapse silently demoting every lookup
   to the generative path would otherwise pass assertion 1 vacuously;
3. ZERO decode-stage spine dispatches across every routed-extractive
   request: the requests run sequentially, so per-request deltas of the
   spine's ``serve_decode`` / ``serve_alloc`` stage counters are exact
   — the fast path must never touch a batcher lane or allocate KV;
4. wire shape: every answer keeps ``{"answer", "sources"}``; ``route``
   appears ONLY on routed-extractive responses (api_contract.json v2);
5. route split: routed-extractive p50 < generative p50 (the ~600ms ->
   ~50ms shape, asserted as an ordering so CI hosts can't flake it).

Writes a ``routing_report.json`` trend artifact (per-request rows,
precision/recall, per-route p50s, live counters) for the CI upload.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
MIX_PATH = os.path.join(REPO, "data", "routing_mix.jsonl")

# tiny REAL decoder (the perf-gate/qos smoke shape): the generative arm
# must pay genuine prefill+decode dispatches or the split proves nothing
OVERRIDES = {
    "encoder.hidden_dim": 64,
    "encoder.num_layers": 1,
    "encoder.num_heads": 4,
    "encoder.mlp_dim": 128,
    "encoder.embed_dim": 64,
    "store.dim": 64,
    "store.shard_capacity": 256,
    "store.serving_index": "tiered",
    "ner.train_steps": 0,
    "decoder.vocab_size": 256,
    "decoder.hidden_dim": 128,
    "decoder.num_layers": 2,
    "decoder.num_heads": 4,
    "decoder.num_kv_heads": 2,
    "decoder.head_dim": 32,
    "decoder.mlp_dim": 256,
    "decoder.max_seq_len": 512,
    "decoder.dtype": "float32",
    "generate.max_new_tokens": 24,
    "generate.prefill_buckets": (64, 128, 256),
    "flags.use_fake_encoder": True,  # retrieval exercised, hash embed
    # first-touch compiles on a loaded CI host can exceed the 8 s
    # production deadline; the smoke measures routing, not cold-start
    "resilience.request_deadline_s": 30.0,
    # the pool's liveness canary is a background 2-token generate — it
    # would race the per-request serve_decode deltas assertion 3 reads,
    # so push it past the smoke's horizon (liveness has its own tests)
    "pool.canary_interval_s": 3600.0,
}

MIN_PRECISION = 0.95
MIN_EXTRACTIVE_ROUTED = 10


def load_mix() -> list:
    mix = []
    with open(MIX_PATH, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if line:
                mix.append(json.loads(line))
    return mix


def seed_corpus(rt, mix: list) -> int:
    """The mix's evidence docs straight into the store — the lexical
    sink indexes them via the registered index-sink path."""
    texts = [ex["doc"] for ex in mix if "doc" in ex]
    ids = [ex["id"] for ex in mix if "doc" in ex]
    emb = rt.encoder.encode_texts(texts)
    rt.store.add(
        emb,
        [
            {"doc_id": i, "source": f"mix/{i}", "text_content": t}
            for i, t in zip(ids, texts)
        ],
    )
    return len(texts)


def _p50(xs: list):
    xs = sorted(xs)
    return round(xs[len(xs) // 2], 1) if xs else None


async def drive(rt, mix: list, errs: list) -> dict:
    import asyncio

    import aiohttp
    from aiohttp import web

    from docqa_tpu.engines.spine import get_spine
    from docqa_tpu.service.app import make_app

    def stage_count(name: str) -> int:
        row = get_spine().stats()["stages"].get(name) or {}
        return int(row.get("count", 0))

    app = make_app(rt)
    runner = web.AppRunner(app)
    await runner.setup()
    site = web.TCPSite(runner, "127.0.0.1", 0)
    await site.start()
    port = site._server.sockets[0].getsockname()[1]
    base = f"http://127.0.0.1:{port}"
    rows = []
    try:
        async with aiohttp.ClientSession() as s:

            async def one(question: str):
                t0 = time.perf_counter()
                async with s.post(
                    f"{base}/ask/", json={"question": question}
                ) as r:
                    body = await r.json()
                    return r.status, body, (time.perf_counter() - t0) * 1e3

            # warm BOTH arms until the real (non-degraded) paths serve:
            # generative pays the prefill/decode compiles, extractive
            # pays the lexical/hybrid program compile
            t_end = time.monotonic() + 300
            while time.monotonic() < t_end:
                st, body, _ = await one("Summarize the admission note.")
                if st == 200 and not body.get("degraded"):
                    break
            else:
                errs.append("generative warmup never served un-degraded")
            while time.monotonic() < t_end:
                st, body, _ = await one(
                    "What is the MRN of patient Okafor?"
                )
                if st == 200 and body.get("route") == "extractive":
                    break
            else:
                errs.append("extractive warmup never served the route")

            # quiescence barrier: a warmup decode whose HTTP answer
            # already resolved can still be retiring chunks on the
            # batcher worker — wait for the decode counter to go flat so
            # the per-request deltas below are attributable
            stable_since, last = time.monotonic(), stage_count(
                "serve_decode"
            )
            while time.monotonic() < t_end:
                await asyncio.sleep(0.1)
                now = stage_count("serve_decode")
                if now != last:
                    stable_since, last = time.monotonic(), now
                elif time.monotonic() - stable_since > 2.0:
                    break

            for ex in mix:
                d0, a0 = stage_count("serve_decode"), stage_count(
                    "serve_alloc"
                )
                st, body, lat_ms = await one(ex["question"])
                if st != 200:
                    errs.append(f"{ex['id']}: HTTP {st}: {body}")
                    continue
                if not ({"answer", "sources"} <= set(body)):
                    errs.append(
                        f"{ex['id']}: wire shape broken: {sorted(body)}"
                    )
                route = body.get("route")
                if route not in (None, "extractive"):
                    errs.append(f"{ex['id']}: unexpected route {route!r}")
                routed_ex = route == "extractive"
                decode_d = stage_count("serve_decode") - d0
                alloc_d = stage_count("serve_alloc") - a0
                if routed_ex and (decode_d or alloc_d):
                    errs.append(
                        f"{ex['id']}: routed-extractive paid device "
                        f"dispatches (serve_decode +{decode_d}, "
                        f"serve_alloc +{alloc_d}) — the decoder-skip "
                        "path regressed"
                    )
                rows.append(
                    {
                        "id": ex["id"],
                        "lang": ex["lang"],
                        "label": ex["label"],
                        "routed": "extractive" if routed_ex else
                        "generative",
                        "latency_ms": round(lat_ms, 1),
                        "degraded": bool(body.get("degraded")),
                        "serve_decode_delta": decode_d,
                        "serve_alloc_delta": alloc_d,
                    }
                )
            async with s.get(f"{base}/api/retrieval") as r:
                routing_live = (await r.json()).get("routing") \
                    if r.status == 200 else None
    finally:
        await runner.cleanup()
    return {"rows": rows, "routing_live": routing_live}


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default="routing_report.json")
    args = ap.parse_args()

    import asyncio

    from docqa_tpu.config import load_config
    from docqa_tpu.service.app import DocQARuntime

    mix = load_mix()
    cfg = load_config(env={}, overrides=dict(OVERRIDES))
    rt = DocQARuntime(cfg).start()
    errs: list = []
    try:
        n_docs = seed_corpus(rt, mix)
        driven = asyncio.run(drive(rt, mix, errs))
    finally:
        rt.stop()
    rows = driven["rows"]

    tp = sum(
        1 for r in rows
        if r["label"] == "extractive" and r["routed"] == "extractive"
    )
    fp = sum(
        1 for r in rows
        if r["label"] == "generative" and r["routed"] == "extractive"
    )
    fn = sum(
        1 for r in rows
        if r["label"] == "extractive" and r["routed"] == "generative"
    )
    precision = tp / max(tp + fp, 1)
    recall = tp / max(tp + fn, 1)
    if len(rows) != len(mix):
        errs.append(f"only {len(rows)}/{len(mix)} requests measured")
    if precision < MIN_PRECISION:
        errs.append(
            f"routing precision {precision:.3f} < {MIN_PRECISION} "
            f"(tp={tp} fp={fp}) — extractive-routed generative "
            "questions are shipping wrong-shaped answers"
        )
    if tp + fp < MIN_EXTRACTIVE_ROUTED:
        errs.append(
            f"only {tp + fp} routed-extractive answers (< "
            f"{MIN_EXTRACTIVE_ROUTED}): the evidence gate is demoting "
            "the lookup traffic and the decoder-skip win is gone"
        )
    ex_lat = [r["latency_ms"] for r in rows if r["routed"] == "extractive"]
    gen_lat = [r["latency_ms"] for r in rows if r["routed"] == "generative"]
    p50_ex, p50_gen = _p50(ex_lat), _p50(gen_lat)
    if p50_ex is not None and p50_gen is not None and p50_ex >= p50_gen:
        errs.append(
            f"route split inverted: routed-extractive p50 {p50_ex}ms >= "
            f"generative p50 {p50_gen}ms — the fast path is not fast"
        )

    report = {
        "n_docs": n_docs,
        "n_requests": len(rows),
        "routing_precision": round(precision, 3),
        "routing_recall": round(recall, 3),
        "confusion": {"tp": tp, "fp": fp, "fn": fn,
                      "tn": len(rows) - tp - fp - fn},
        "p50_ms": {"extractive": p50_ex, "generative": p50_gen},
        "split_ratio": (
            round(p50_gen / p50_ex, 1) if p50_ex and p50_gen else None
        ),
        "routing_live": driven["routing_live"],
        "rows": rows,
        "errors": errs,
        "pass": not errs,
    }
    with open(args.out, "w", encoding="utf-8") as f:
        json.dump(report, f, indent=2)
        f.write("\n")
    print(
        f"routing_smoke: precision {precision:.3f} recall {recall:.3f} "
        f"(tp={tp} fp={fp} fn={fn}); p50 extractive {p50_ex}ms vs "
        f"generative {p50_gen}ms (x{report['split_ratio']}); "
        f"report -> {args.out}"
    )
    if errs:
        for e in errs:
            print(f"FAIL: {e}", file=sys.stderr)
        return 1
    print(
        "routing_smoke PASS: precision floor held, decoder-skip paid "
        "zero decode/alloc dispatches, route split ordered"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
