#!/usr/bin/env python
"""Dump flight-recorder timelines (docs/OBSERVABILITY.md).

Against a running service:

    # summaries + full JSON timelines of the recent (or anomalous) ring
    python scripts/trace_dump.py http://127.0.0.1:8000 --out traces.json
    python scripts/trace_dump.py http://127.0.0.1:8000 --anomalous --out bad.json

    # ONE request's Chrome trace — load the file at https://ui.perfetto.dev
    python scripts/trace_dump.py http://127.0.0.1:8000 t-000007 --out one.json

Self-contained smoke (the CI artifact): boot a fake-mode runtime in
process, drive one ingest + one /ask over real HTTP, and export the
/ask request's Chrome trace:

    python scripts/trace_dump.py --smoke --out ask_trace.json

Exits non-zero when the smoke trace is structurally broken (no events,
no linked spans), when ``GET /metrics`` fails the strict Prometheus
line-lint (``obs/expo.py``), or when ``GET /api/telemetry`` serves no
series — so CI fails loudly instead of archiving an empty file.
"""

import argparse
import json
import os
import sys
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def fetch_json(url: str, timeout: float = 30.0):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return json.loads(resp.read().decode())


def dump_from_service(base: str, trace_id, anomalous: bool, out: str) -> int:
    if trace_id:
        payload = fetch_json(f"{base}/api/trace/{trace_id}?format=chrome")
        kind = "chrome-trace"
    else:
        flag = "?anomalous=1&limit=100" if anomalous else "?limit=100"
        summaries = fetch_json(f"{base}/api/traces{flag}")
        payload = {
            "summaries": summaries,
            "timelines": [
                fetch_json(f"{base}/api/trace/{row['trace_id']}")
                for row in summaries
            ],
        }
        kind = f"{len(summaries)} timeline(s)"
    with open(out, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=1)
    print(f"wrote {kind} to {out}")
    return 0


def smoke(out: str) -> int:
    """Fake-mode runtime, one /ask over real HTTP, Chrome trace out."""
    import asyncio

    import jax

    jax.config.update("jax_platforms", "cpu")

    from docqa_tpu.config import load_config
    from docqa_tpu.service.app import DocQARuntime, make_app

    cfg = load_config(env={}, overrides={
        "flags.use_fake_llm": True,
        "flags.use_fake_encoder": True,
        "encoder.embed_dim": 64,
        "store.dim": 64,
        "store.shard_capacity": 256,
        "ner.hidden_dim": 32,
        "ner.num_layers": 1,
        "ner.num_heads": 2,
        "ner.mlp_dim": 64,
        "ner.train_steps": 0,
    })
    rt = DocQARuntime(cfg).start()

    async def drive():
        import aiohttp
        from aiohttp import web

        app = make_app(rt)
        runner = web.AppRunner(app)
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        port = site._server.sockets[0].getsockname()[1]
        base = f"http://127.0.0.1:{port}"
        try:
            async with aiohttp.ClientSession() as s:
                async with s.post(
                    f"{base}/ingest/?wait=1",
                    json={
                        "filename": "smoke.txt",
                        "text": "Aspirin 100 mg daily. BP 130/85 mmHg.",
                        "patient_id": "p-smoke",
                    },
                ) as r:
                    assert r.status == 200, await r.text()
                async with s.post(
                    f"{base}/ask/", json={"question": "aspirin dose?"}
                ) as r:
                    assert r.status == 200, await r.text()
                    trace_id = r.headers.get("X-Trace-Id")
                assert trace_id, "no X-Trace-Id on the /ask response"
                timeline = await (
                    await s.get(f"{base}/api/trace/{trace_id}")
                ).json()
                chrome = await (
                    await s.get(
                        f"{base}/api/trace/{trace_id}?format=chrome"
                    )
                ).json()
                listing = await (await s.get(f"{base}/api/traces")).json()
                # Prometheus exposition over REAL HTTP bytes, strict
                # line-lint (CI has no promtool; the grammar lives in
                # obs/expo.py and tests/test_telemetry.py pins it)
                async with s.get(f"{base}/metrics") as r:
                    assert r.status == 200, await r.text()
                    prom = await r.text()
                tele = await (await s.get(f"{base}/api/telemetry")).json()
        finally:
            await runner.cleanup()
        return timeline, chrome, listing, prom, tele

    try:
        timeline, chrome, listing, prom, tele = asyncio.run(drive())
    finally:
        rt.stop()

    from docqa_tpu.obs.expo import lint_prometheus_text

    problems = lint_prometheus_text(prom)
    n_series = len(tele.get("series", {}))
    print(
        f"/metrics: {len(prom.splitlines())} line(s), "
        f"{len(problems)} lint problem(s); /api/telemetry: "
        f"{n_series} series"
    )
    if problems:
        for p in problems[:10]:
            print(f"  {p}", file=sys.stderr)
        return 1
    if n_series == 0:
        print("telemetry served no series", file=sys.stderr)
        return 1

    with open(out, "w", encoding="utf-8") as f:
        json.dump(chrome, f, indent=1)
    n_events = len(chrome.get("traceEvents", []))
    n_spans = len(timeline.get("spans", []))
    print(
        f"smoke /ask trace {timeline.get('trace_id')}: {n_spans} span(s), "
        f"coverage {timeline.get('coverage')}, {n_events} Chrome event(s), "
        f"{len(listing)} trace(s) in the recorder -> {out}"
    )
    # structural gates only: the fake-llm path is sub-millisecond, so a
    # coverage threshold would gate on scheduler noise — bench gates the
    # real ≥95% figure on real decode timelines
    if n_events == 0 or n_spans < 2:
        print("smoke trace is structurally empty", file=sys.stderr)
        return 1
    return 0


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("base_url", nargs="?", help="running service base URL")
    ap.add_argument("trace_id", nargs="?", help="one trace id (Chrome out)")
    ap.add_argument("--anomalous", action="store_true",
                    help="dump the always-keep anomalous ring")
    ap.add_argument("--smoke", action="store_true",
                    help="self-contained fake-mode /ask trace export")
    ap.add_argument("--out", default="traces.json")
    args = ap.parse_args()
    if args.smoke:
        return smoke(args.out)
    if not args.base_url:
        ap.error("base_url required (or --smoke)")
    return dump_from_service(
        args.base_url.rstrip("/"), args.trace_id, args.anomalous, args.out
    )


if __name__ == "__main__":
    sys.exit(main())
