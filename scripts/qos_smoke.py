#!/usr/bin/env python
"""Multi-tenant QoS smoke (docqa-qos; docs/OPERATIONS.md "Protect
interactive traffic under overload") — the CI-blocking A/B proof that
the policy layer actually protects interactive latency AND that
preemption never corrupts accounting.

Two deterministic arms drive the SAME overload shape — a batch long
pinning 11+ of an overcommitted pool's 16 KV blocks, then a closed-loop
stream of interactive shorts — through a tiny CPU batcher:

* **OFF** (``qos=None``): the pre-QoS FIFO baseline.  Interactive
  shorts block behind the batch long's residency, so their p95 is
  coupled to batch runtime.
* **ON** (``preemption="on"``): each short evicts the long's KV
  (victim requeued with generated-so-far tokens preserved) and runs
  immediately; the long still retires with its full token count.

Blocking assertions, all structural (no wall-clock thresholds between
machines — the only timing claim is ON-arm p95 < OFF-arm p95, which the
geometry forces by orders of magnitude):

1. zero lost requests in both arms: every submission completes or
   fails TYPED; the ON arm's preempted long completes with exactly
   ``max_new`` tokens (token-preserving re-prefill);
2. zero leaks in both arms: ``blocks_used == 0`` after drain and the
   block-second billing identity holds to float tolerance;
3. the ON arm exercised preemption (``qos_preempted`` moved) and
   billed the victim's wasted hold to ``preempted_block_seconds``;
4. SLO-burn deferral is live and relaxes: with a firing probe a batch
   submission raises ``DeferredByPolicy``; with the probe clear the
   same submission completes;
5. protection: ON-arm interactive p95 < OFF-arm interactive p95.

Writes a ``qos_report.json`` trend artifact (per-arm latencies,
counters, billing deltas, protection ratio) for the CI upload step.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def build_engine(seed: int):
    from docqa_tpu.config import DecoderConfig, GenerateConfig
    from docqa_tpu.engines.generate import GenerateEngine

    cfg = DecoderConfig(
        vocab_size=256,
        hidden_dim=128,
        num_layers=2,
        num_heads=4,
        num_kv_heads=2,
        head_dim=32,
        mlp_dim=256,
        max_seq_len=512,
        dtype="float32",
    )
    gen = GenerateConfig(
        temperature=0.0, prefill_buckets=(32, 64), eos_id=2,
        max_new_tokens=32,
    )
    return GenerateEngine(cfg, gen, seed=seed)


N_INTERACTIVE = 6
N_BACKGROUND = 2
BATCH_MAX_NEW = 48
LONG_PROMPT = [(3 + i * 7) % 250 + 1 for i in range(144)]


def _short(i: int):
    return [(5 + i * 3 + j * 11) % 250 + 1 for j in range(96)]


def run_arm(engine, qos, errs: list) -> dict:
    """One overload window; returns the arm's evidence row.  Structural
    failures append to ``errs`` (the arm still reports)."""
    from docqa_tpu import obs
    from docqa_tpu.engines.serve import ContinuousBatcher
    from docqa_tpu.runtime.metrics import DEFAULT_REGISTRY

    label = "on" if qos is not None else "off"
    ledger = obs.DEFAULT_COST_LEDGER
    before = ledger.class_totals()
    c0 = {
        k: DEFAULT_REGISTRY.counter(k).value
        for k in ("qos_preempted", "qos_deferred")
    }
    b = ContinuousBatcher(
        engine, n_slots=3, chunk=8, cache_len=256, kv_block_size=16,
        kv_pool_tokens=256, prefix_cache=False, qos=qos,
    )
    lost = 0
    try:
        b.warmup(buckets=engine.gen.prefill_buckets[:1])
        bg_handles = [
            b.submit_ids(
                [3 + i, 5, 9], max_new_tokens=4, req_class="background"
            )
            for i in range(N_BACKGROUND)
        ]
        h_batch = b.submit_ids(
            LONG_PROMPT, max_new_tokens=BATCH_MAX_NEW, req_class="batch"
        )
        # the long must pin 11+ of the 16 blocks before the interactive
        # stream arrives — a 96-token short then cannot fit beside it
        t_dead = time.time() + 30
        while time.time() < t_dead:
            if (
                b.kv_block_occupancy()["blocks_used"] >= 11
                or h_batch._req.done.is_set()
            ):
                break
            time.sleep(0.005)
        lats = []
        for i in range(N_INTERACTIVE):
            t0 = time.perf_counter()
            try:
                b.submit_ids(
                    _short(i), max_new_tokens=8, req_class="interactive"
                ).result(timeout=120)
                lats.append((time.perf_counter() - t0) * 1e3)
            except Exception as e:  # typed shed would land here
                lost += 1
                errs.append(f"[{label}] interactive {i} failed: {e!r}")
        try:
            batch_out = h_batch.result(timeout=300)
        except Exception as e:
            batch_out = []
            lost += 1
            errs.append(f"[{label}] batch long failed: {e!r}")
        for i, h in enumerate(bg_handles):
            try:
                h.result(timeout=120)
            except Exception as e:
                lost += 1
                errs.append(f"[{label}] background {i} failed: {e!r}")
        # zero-lost: the (possibly preempted) long must carry its FULL
        # decode budget — token-preserving re-prefill, not a truncation
        if len(batch_out) != BATCH_MAX_NEW:
            errs.append(
                f"[{label}] batch long retired {len(batch_out)} tokens, "
                f"wanted {BATCH_MAX_NEW} (re-prefill lost progress?)"
            )
        t_dead = time.time() + 30
        while b.n_active and time.time() < t_dead:
            time.sleep(0.005)
        used = b.kv_block_occupancy()["blocks_used"]
        if used:
            errs.append(f"[{label}] leak: {used} blocks held after drain")
        bs = b.block_seconds()
    finally:
        b.stop()
        residual = b.block_seconds()["residual"]
    if abs(residual) > max(1e-6, 1e-9 * bs["total"]):
        errs.append(
            f"[{label}] billing identity broken: residual {residual:.3e}"
        )
    after = ledger.class_totals()

    def d(cls, key):
        return after.get(cls, {}).get(key, 0.0) - before.get(cls, {}).get(
            key, 0.0
        )

    lats_sorted = sorted(lats)
    p95 = (
        lats_sorted[max(0, int(round(0.95 * len(lats_sorted))) - 1)]
        if lats_sorted
        else None
    )
    return {
        "qos": label,
        "interactive_completed": len(lats),
        "interactive_p95_ms": round(p95, 1) if p95 is not None else None,
        "interactive_lat_ms": [round(x, 1) for x in lats],
        "batch_tokens": len(batch_out),
        "lost": lost,
        "preempted": int(
            DEFAULT_REGISTRY.counter("qos_preempted").value
            - c0["qos_preempted"]
        ),
        "deferred": int(
            DEFAULT_REGISTRY.counter("qos_deferred").value
            - c0["qos_deferred"]
        ),
        "batch_preempted_block_seconds": round(
            d("batch", "preempted_block_seconds"), 4
        ),
        "kv_residual_after_stop": residual,
    }


def run_deferral_probe(engine, errs: list) -> dict:
    """Deterministic deferral check: force the SLO probe to fire, show a
    batch submission is deferred TYPED; clear it, show the same
    submission completes (the policy relaxes — no un-defer edge)."""
    from docqa_tpu.config import QoSConfig
    from docqa_tpu.engines.serve import ContinuousBatcher, DeferredByPolicy

    firing: list = []
    b = ContinuousBatcher(
        engine, n_slots=2, chunk=8, cache_len=256, prefix_cache=False,
        qos=QoSConfig(preemption="off"),
    )
    deferred_typed = False
    relaxed_ok = False
    try:
        b.set_slo_probe(lambda: list(firing))
        b.warmup(buckets=engine.gen.prefill_buckets[:1])
        firing.append("ask_p95_latency")
        try:
            b.submit_ids([5, 9, 11], max_new_tokens=4, req_class="batch")
            errs.append("deferral: batch admitted while SLO burning")
        except DeferredByPolicy:
            deferred_typed = True
        # interactive must be untouched by the burn
        b.submit_ids(
            [7, 5, 9], max_new_tokens=4, req_class="interactive"
        ).result(timeout=120)
        firing.clear()
        out = b.submit_ids(
            [5, 9, 11], max_new_tokens=4, req_class="batch"
        ).result(timeout=120)
        relaxed_ok = len(out) > 0
        if not relaxed_ok:
            errs.append("deferral: batch empty after burn cleared")
    finally:
        b.stop()
    if not deferred_typed:
        errs.append("deferral: DeferredByPolicy never raised under burn")
    return {"deferred_typed": deferred_typed, "relaxed_ok": relaxed_ok}


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--out", default="qos_report.json")
    args = ap.parse_args()

    from docqa_tpu.config import QoSConfig

    engine = build_engine(args.seed)
    errs: list = []
    arm_off = run_arm(engine, None, errs)
    arm_on = run_arm(
        engine, QoSConfig(preemption="on", aging_floor_s=2.0), errs
    )
    deferral = run_deferral_probe(engine, errs)

    if arm_on["preempted"] < 1:
        errs.append(
            "on-arm never preempted: the collision geometry guarantees "
            "pressure, so the eviction path is broken"
        )
    elif arm_on["batch_preempted_block_seconds"] <= 0.0:
        errs.append(
            "preemption fired but no wasted hold reached "
            "preempted_block_seconds (billing attribution broken)"
        )
    p_off, p_on = arm_off["interactive_p95_ms"], arm_on["interactive_p95_ms"]
    if p_off is None or p_on is None:
        errs.append("missing interactive p95 (an arm lost its stream)")
    elif p_on >= p_off:
        errs.append(
            f"policy ON did not protect interactive p95: {p_on}ms on "
            f">= {p_off}ms off"
        )
    report = {
        "seed": args.seed,
        "arms": {"off": arm_off, "on": arm_on},
        "deferral": deferral,
        "protection_ratio": (
            round(p_off / p_on, 2) if p_off and p_on else None
        ),
        "errors": errs,
        "pass": not errs,
    }
    with open(args.out, "w", encoding="utf-8") as f:
        json.dump(report, f, indent=2)
        f.write("\n")
    print(
        f"qos_smoke: interactive p95 {p_off}ms (off) -> {p_on}ms (on), "
        f"protection x{report['protection_ratio']}; "
        f"{arm_on['preempted']} preemption(s) billing "
        f"{arm_on['batch_preempted_block_seconds']} block-s, "
        f"deferral typed={deferral['deferred_typed']} "
        f"relaxed={deferral['relaxed_ok']}; report -> {args.out}"
    )
    if errs:
        for e in errs:
            print(f"FAIL: {e}", file=sys.stderr)
        return 1
    print("qos_smoke PASS: zero lost, zero leaks, interactive protected")
    return 0


if __name__ == "__main__":
    sys.exit(main())
