#!/usr/bin/env python
"""Standalone driver for the mesh-sharded tiered retrieval sweep
(``bench.py:run_shard_scale`` — docqa-meshindex, ROADMAP item 2).

Runs the 1M→10M synthetic clustered sweep on the 8-virtual-device CPU
mesh (or the real mesh under a TPU backend) and MERGES the resulting
``shard_scale`` section into ``bench_details.json`` without touching the
other sections — the full ``bench.py`` run produces the same section
in-line; this script exists so the slow sweep can be (re)measured
without re-running the whole matrix::

    python scripts/shard_scale_bench.py                      # full sweep
    python scripts/shard_scale_bench.py --scales 1000000     # quick look
    python scripts/shard_scale_bench.py --out -              # stdout only
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument(
        "--scales", default="1000000,2000000,5000000,10000000",
        help="comma-separated corpus sizes",
    )
    ap.add_argument("--dim", type=int, default=64)
    ap.add_argument(
        "--nprobes", default="4,8,16,32,64",
        help="comma-separated frontier nprobe grid",
    )
    ap.add_argument("--budget-s", type=float, default=None,
                    help="wall budget; later scales skip when exhausted")
    ap.add_argument(
        "--out",
        default=os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "bench_details.json",
        ),
        help="bench_details.json to merge into ('-' = stdout only)",
    )
    args = ap.parse_args()

    import bench  # noqa: E402  (path inserted above; sets nothing up)
    import jax

    on_tpu = jax.default_backend() == "tpu"
    result = bench.run_shard_scale(
        scales=tuple(int(s) for s in args.scales.split(",")),
        dim=args.dim,
        nprobes=tuple(int(p) for p in args.nprobes.split(",")),
        budget_s=args.budget_s,
        on_tpu=on_tpu,
    )
    if args.out == "-":
        json.dump(result, sys.stdout, indent=1)
        print()
        return 0
    details = {}
    if os.path.exists(args.out):
        with open(args.out, encoding="utf-8") as f:
            details = json.load(f)
    details["shard_scale"] = result
    with open(args.out, "w", encoding="utf-8") as f:
        json.dump(details, f, indent=2)
    print(f"shard_scale section merged -> {args.out}")
    dec = result.get("nprobe_decision", {})
    print(
        f"nprobe decision: chosen={dec.get('chosen')} "
        f"(target {dec.get('recall_target')}, qualified "
        f"{dec.get('qualified_nprobes')})"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
