"""Benchmark suite: the full BASELINE.md config matrix on real TPU.

Headline (the driver contract — exactly ONE JSON line on stdout):
  {"metric": "qa_e2e_p50_ms", "value": p50, "unit": "ms", "vs_baseline": r}
measuring the north-star metric — end-to-end QA latency over a 1M-chunk
HBM-resident corpus, target <1 s p50 (the reference publishes no numbers,
BASELINE.md: "measured, not inherited"; vs_baseline = 1000 / p50_ms).

The rest of the BASELINE.json config matrix is measured in the same run,
logged to stderr, and written to ``bench_details.json``:

  1. retrieval: exact top-k latency at 1M chunks, encode-only, and the
     fused one-dispatch text->top-k path
  2. deid: NER PHI tagging throughput, batch = 32 docs
  3. generator: greedy decode tokens/s + HBM-bandwidth utilization for
     the 1.1B-class serving model in bf16 AND int8 (the serving default —
     the headline e2e runs on int8, with a bf16 e2e alongside for round-1
     comparability), plus Mistral-7B-class attempts in bf16 and int8
     (one v5e chip has 16 GB HBM; if the bf16 7B OOMs that is recorded)
  4. summarizer: 5-chunk patient summary latency on the decoder backend
     and on the dedicated BART-class encoder-decoder
  5. full RAG under load: sustained QPS through the continuous batcher
     (target 16) with per-request latency

Corpus vectors are drawn from a 2000-center mixture (embedding-like
cluster structure) so the IVF recall measurement means something —
uniform random vectors are IVF's degenerate worst case and nothing like
real sentence embeddings.  IVF/tiered recall@10 + latency vs exact are
reported alongside config 1.
"""

from __future__ import annotations

import gc
import json
import os
import sys
import time

import numpy as np

DETAILS: dict = {}
V5E_HBM_GBPS = 819.0  # v5e chip peak HBM bandwidth


def log(msg: str) -> None:
    print(f"# {msg}", file=sys.stderr, flush=True)


def flush_details() -> None:
    """Write bench_details.json NOW — called after every section so a
    driver-side timeout mid-run still leaves every completed measurement
    on disk."""
    try:
        with open(
            os.path.join(
                os.path.dirname(os.path.abspath(__file__)),
                "bench_details.json",
            ),
            "w",
        ) as f:
            json.dump(DETAILS, f, indent=2)
    except Exception as e:
        log(f"details write failed: {e!r}")


def timed(fn, n=1):
    t0 = time.perf_counter()
    for _ in range(n):
        out = fn()
    return (time.perf_counter() - t0) / n, out


def _unit(x):
    return x / np.linalg.norm(x, axis=-1, keepdims=True)


def make_centers(rng, n_centers, dim):
    """Hierarchical center set: super-topics → topics, with TOTAL-norm
    noise scales (a per-dimension sigma in 384-d would drown the cluster
    signal entirely — noise norm grows with sqrt(d))."""
    supers = _unit(rng.standard_normal((40, dim)).astype(np.float32))
    return _unit(
        supers[rng.integers(0, len(supers), n_centers)]
        + 0.6 * _unit(rng.standard_normal((n_centers, dim)).astype(np.float32))
    )


def clustered_vectors(rng, n, dim, centers):
    """Embedding-like corpus: cos(point, its center) ≈ 0.89."""
    noise = 0.5 * _unit(rng.standard_normal((n, dim)).astype(np.float32))
    return _unit(centers[rng.integers(0, len(centers), n)] + noise).astype(
        np.float32
    )


def dispatch_health(tag: str) -> None:
    """Record the dispatch+sync median under DETAILS["dispatch_ms"].

    On the tunneled client the FIRST device→host fetch of the process
    flips every later synchronization to a flat ~66 ms (async dispatch
    chains stay free — docs/PERF.md §1); local backends read ~0.02 ms
    throughout.  Recording the value at several milestones documents
    which regime each section was measured in."""
    import statistics

    import jax
    import jax.numpy as jnp

    try:
        f = jax.jit(lambda a, b: a @ b)
        x = jnp.ones((128, 128), jnp.bfloat16)
        f(x, x).block_until_ready()
        lat = []
        for _ in range(15):
            t0 = time.perf_counter()
            f(x, x).block_until_ready()
            lat.append((time.perf_counter() - t0) * 1e3)
        DETAILS.setdefault("dispatch_ms", {})[tag] = round(
            statistics.median(lat), 3
        )
    except Exception as e:  # never let the probe cost a section
        DETAILS.setdefault("dispatch_ms", {})[tag] = repr(e)[:80]


def param_bytes(params) -> int:
    return int(sum(np.prod(p.shape) * p.dtype.itemsize for p in params.values()))


def _device_backend_alive(timeout_s: float = 150.0) -> bool:
    """Probe the accelerator from a SUBPROCESS: a dead tunnel hangs
    ``jax.devices()`` indefinitely, and an in-process hang would eat the
    driver's whole bench budget with no JSON line to show for it."""
    import subprocess
    import sys as _sys

    try:
        r = subprocess.run(
            [_sys.executable, "-c", "import jax; jax.devices()"],
            timeout=timeout_s,
            capture_output=True,
        )
        return r.returncode == 0
    except Exception:
        return False


def _device_backend_alive_retrying(
    attempts: int = 4, probe_timeout_s: float = 150.0, backoff_s: float = 60.0
) -> bool:
    """Bounded retry/backoff around the probe: a transient tunnel outage at
    bench start must not forfeit the whole round to a CPU smoke run (it did,
    twice).  Budget: ~4 probes over ~13 min — small next to the bench window,
    large next to a tunnel blip."""
    for i in range(attempts):
        if _device_backend_alive(probe_timeout_s):
            if i:
                log(f"accelerator answered on probe attempt {i + 1}")
            return True
        if i + 1 < attempts:
            log(
                f"accelerator probe {i + 1}/{attempts} failed; "
                f"retrying in {backoff_s:.0f}s"
            )
            time.sleep(backoff_s)
    return False


def _start_stall_watchdog(stall_min: float = 30.0) -> None:
    """Abort (exit 3) if NO section lands a measurement for ``stall_min``
    minutes.

    The start-of-run probe retry cannot help once the run is under way: a
    tunnel outage mid-run leaves the axon client sleeping in an internal
    retry loop forever — observed live: a bench 25+ minutes into "one real
    chip" with zero log output, zero IO, and a main thread parked in
    ``clock_nanosleep``.  Progress is defined as DETAILS changing (every
    section writes there, and the corpus loop writes per-block
    breadcrumbs); on stall the watchdog flushes what was measured and
    exits 3 so the outer wrapper (``_run_with_fallback``) can still get
    the driver its one JSON line from a CPU smoke rerun."""
    import threading

    def snap() -> str:
        # dict(DETAILS) snapshots atomically under the GIL; dumping the
        # copy cannot race the main thread's inserts.  The bare fallback
        # must be infallible — an exception here would kill the daemon
        # thread silently and un-watch the rest of the run.
        try:
            return json.dumps(dict(DETAILS), sort_keys=True, default=str)
        except Exception:
            return f"len={len(DETAILS)}"

    state = {"snap": snap(), "t": time.time()}

    def run() -> None:
        while True:
            time.sleep(60)
            try:
                cur = snap()
                if cur != state["snap"]:
                    state["snap"], state["t"] = cur, time.time()
                elif time.time() - state["t"] > stall_min * 60:
                    log(
                        f"WATCHDOG: no measurement progress in "
                        f"{stall_min:.0f} min — device backend likely hung "
                        "mid-run; aborting (exit 3) so the smoke fallback "
                        "can run"
                    )
                    DETAILS["watchdog_abort"] = True
                    flush_details()
                    os._exit(3)
            except Exception as e:  # the watchdog must outlive anything
                log(f"watchdog iteration error (ignored): {e!r}")

    threading.Thread(target=run, daemon=True, name="bench-watchdog").start()


def _run_with_fallback() -> int:
    """Outer wrapper: run the real bench as a child process; if it exits
    without having printed the headline JSON line (watchdog abort, crash,
    or outer-budget timeout), rerun in the forced-CPU smoke configuration
    so the driver ALWAYS receives its one line.  The inner run is selected
    with ``DOCQA_BENCH_INNER=1``."""
    import subprocess
    import threading

    def run_child(extra_env: dict, budget_s: float) -> bool:
        env = dict(os.environ, DOCQA_BENCH_INNER="1", **extra_env)
        p = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__)],
            env=env,
            stdout=subprocess.PIPE,
            text=True,
            bufsize=1,
        )
        got_json = [False]

        def forward() -> None:
            for line in p.stdout:
                sys.stdout.write(line)
                sys.stdout.flush()
                s = line.strip()
                if s.startswith("{"):
                    try:
                        json.loads(s)
                        got_json[0] = True
                    except ValueError:
                        pass

        t = threading.Thread(target=forward, daemon=True)
        t.start()
        deadline = time.time() + budget_s
        while p.poll() is None:
            if time.time() > deadline and not got_json[0]:
                log(
                    f"outer budget ({budget_s:.0f}s) exhausted with no "
                    "headline line — killing the bench child"
                )
                p.kill()
                break
            time.sleep(5)
        p.wait()
        t.join(timeout=30)
        return got_json[0]

    budget = float(os.environ.get("DOCQA_BENCH_OUTER_BUDGET_S", "5400"))
    if run_child({}, budget):
        return 0
    log("bench run produced no headline — rerunning as forced-CPU smoke")
    # preserve the aborted real run's partial measurements (the watchdog
    # flushed them) — the smoke child writes the same bench_details.json
    details = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "bench_details.json"
    )
    if os.path.exists(details):
        try:
            os.replace(details, details + ".partial")
            log(f"partial real-run details saved to {details}.partial")
        except OSError as e:
            log(f"could not preserve partial details: {e!r}")
    if run_child(
        {"DOCQA_BENCH_FORCE_CPU": "1", "DOCQA_BENCH_SMALL": "1"}, 1800.0
    ):
        return 0
    log("smoke fallback also failed to produce a headline")
    return 1


def _bench_lock(max_wait_s: float = 3600.0) -> None:
    """Cooperative single-runner lock: two benches sharing one chip OOM
    each other into false negatives.  If another live bench holds the
    lock, wait for it (finishing late beats colliding); a stale lock
    (dead pid) is ignored."""
    # flock, not a pid file: acquisition is atomic in the kernel, release is
    # automatic on process death (no stale-pid detection, no TOCTOU between
    # judging a lock stale and unlinking it), and the file itself is never
    # removed so every bench locks the same inode.
    import fcntl

    try:
        fd = os.open("/tmp/docqa_bench.lock", os.O_CREAT | os.O_WRONLY, 0o666)
    except Exception:
        return  # lock is cooperative; never let it kill the bench
    deadline = time.time() + max_wait_s
    while True:
        try:
            fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
            # keep fd open for the process lifetime: closing it would drop
            # the lock (module global, intentionally never closed)
            globals()["_bench_lock_fd"] = fd
            return
        except OSError:
            if time.time() > deadline:
                log("bench lock held past wait budget; proceeding")
                return
            log("bench lock held by another bench; waiting")
            time.sleep(30)


def main() -> None:
    _bench_lock()
    _start_stall_watchdog()
    force_cpu = os.environ.get("DOCQA_BENCH_FORCE_CPU") == "1"
    if force_cpu or not _device_backend_alive_retrying():
        # degrade honestly: a CPU smoke run labeled as such beats a hang
        log(
            "forced-CPU smoke rerun"
            if force_cpu
            else "accelerator backend unreachable (tunnel down?) — "
            "falling back to the CPU smoke configuration"
        )
        os.environ["DOCQA_BENCH_SMALL"] = "1"
        import jax

        jax.config.update("jax_platforms", "cpu")

    import jax

    backend = jax.default_backend()
    on_tpu = backend == "tpu"
    small = (not on_tpu) or os.environ.get("DOCQA_BENCH_SMALL") == "1"

    from docqa_tpu.config import (
        DecoderConfig,
        EncoderConfig,
        GenerateConfig,
        NERConfig,
        StoreConfig,
        SummarizerConfig,
    )
    from docqa_tpu.engines.encoder import EncoderEngine
    from docqa_tpu.engines.generate import GenerateEngine
    from docqa_tpu.index.store import VectorStore
    from docqa_tpu.runtime.mesh import make_mesh

    n_chunks = 20_000 if small else 1_000_000
    max_new = 16 if small else 64
    n_queries = 5 if small else 20
    # 7B e2e sample count: 5-sample p50s swung 445-683 ms run to run on
    # the tunnel; 15 asks cost ~7 s per spec_k and cut that spread
    n_e2e_7b = min(15, n_queries)
    dec_cfg = (
        DecoderConfig()  # smoke size
        if small
        else DecoderConfig(  # ~1.1B-param class serving model
            vocab_size=32000,
            hidden_dim=2048,
            num_layers=16,
            num_heads=16,
            num_kv_heads=8,
            head_dim=128,
            mlp_dim=5632,
            max_seq_len=4096,
        )
    )

    mesh = make_mesh() if jax.device_count() > 1 else None
    DETAILS["backend"] = backend
    DETAILS["n_chunks"] = n_chunks
    # sections that are slow and NOT headline-critical (long compiles,
    # training) run after the summary line is already printed, so a
    # driver-side timeout cannot cost the round its headline
    late_sections = []

    # ---- corpus: 1M clustered chunks, HBM-resident -------------------------
    rng = np.random.default_rng(0)
    dim = 384
    centers = make_centers(rng, 2000, dim)

    encoder = EncoderEngine(EncoderConfig(), mesh=mesh)
    # token_width: per-row generator tokens in HBM (+512 MB at 1M rows)
    # feed the single-sync fused RAG path measured as qa_e2e*_fused
    store = VectorStore(
        StoreConfig(shard_capacity=max(n_chunks, 16384), token_width=128),
        mesh=mesh,
    )
    t0 = time.perf_counter()
    block = 131_072
    for start in range(0, n_chunks, block):
        n = min(block, n_chunks - start)
        vecs = clustered_vectors(rng, n, dim, centers)
        tok_lens = rng.integers(60, 128, n).astype(np.int32)
        tok_rows = rng.integers(5, 30_000, (n, 128)).astype(np.int32)
        tok_rows[np.arange(128)[None, :] >= tok_lens[:, None]] = 0
        store.add(
            vecs,
            [
                {"doc_id": f"d{i}", "source": f"chunk {i}", "type": "kb"}
                for i in range(start, start + n)
            ],
            token_rows=tok_rows,
            token_lens=tok_lens,
        )
        # watchdog breadcrumb: each ~200 MB block transfer is progress
        DETAILS["ingest_rows"] = start + n
    log(f"corpus: {n_chunks} chunks ingested in {time.perf_counter()-t0:.1f}s")
    dispatch_health("after_corpus")

    gen = GenerateEngine(dec_cfg, mesh=mesh)

    # ---- config 1: retrieval (encode + exact top-k at 1M) -------------------
    q_texts = [
        f"What formula treats syndrome {i} with highest score and why?"
        for i in range(n_queries + 2)
    ]
    from docqa_tpu.engines.retrieve import FusedRetriever

    retriever = FusedRetriever(encoder, store)
    emb0 = encoder.encode_texts([q_texts[0]])  # compile
    store.search(emb0, k=3)
    store.search(emb0, k=10)  # the timed shape (jit key includes k)
    retriever.search_texts([q_texts[0]], k=3)  # compile fused (headline shape)
    retriever.search_texts([q_texts[0]], k=10)
    t_enc, _ = timed(lambda: encoder.encode_texts([q_texts[1]]), n=5)
    t_search, _ = timed(lambda: store.search(emb0, k=10), n=5)
    t_fused, _ = timed(
        lambda: retriever.search_texts([q_texts[1]], k=10), n=5
    )
    DETAILS["retrieval"] = {
        "encode_ms": round(t_enc * 1e3, 2),
        "exact_top10_ms": round(t_search * 1e3, 2),
        "fused_query_top10_ms": round(t_fused * 1e3, 2),
    }
    log(
        f"config1 retrieval: encode {t_enc*1e3:.1f}ms, "
        f"exact top-10 @ {n_chunks}: {t_search*1e3:.1f}ms, "
        f"fused text->top-10: {t_fused*1e3:.1f}ms"
    )
    flush_details()

    # ---- IVF / tiered: recall@10 + latency vs exact -------------------------
    try:
        from docqa_tpu.index.tiered import TieredIndex

        tiered = TieredIndex(
            store,
            nprobe=32,
            min_rows=10_000,
            rebuild_tail_rows=10 * n_chunks,  # no background churn mid-bench
            n_clusters=None if small else 1000,
        )
        t0 = time.perf_counter()
        tiered.rebuild()
        t_build = time.perf_counter() - t0
        probes = clustered_vectors(rng, 20, dim, centers)
        exact_res = store.search(probes, k=10)
        tiered.search(probes, k=10)  # compile at the TIMED batch shape
        t_tier, tier_res = timed(lambda: tiered.search(probes, k=10))
        hits = total = 0
        for e_row, a_row in zip(exact_res, tier_res):
            want = {r.row_id for r in e_row}
            hits += len(want & {r.row_id for r in a_row})
            total += len(want)
        t_exact20, _ = timed(lambda: store.search(probes, k=10))
        # batch-1 is IVF's regime: a single query probes nprobe*cap rows
        # (~3% of the corpus) while exact must stream every row; at batch-20
        # the exact matmul amortizes its one corpus read over all queries
        # and wins — both numbers are reported so the crossover is explicit
        one = probes[:1]
        store.search(one, k=10)
        tiered.search(one, k=10)  # compile batch-1 shapes
        t_tier1, _ = timed(lambda: tiered.search(one, k=10), n=5)
        t_exact1, _ = timed(lambda: store.search(one, k=10), n=5)
        # the ONE-dispatch text->tiered program serving uses when
        # serving_index="tiered" (encode + IVF probe + tail in one XLA
        # program) — measured against the fused-exact number in
        # DETAILS["retrieval"] so the serving-policy crossover table in
        # docs/PERF.md §4 can be filled from one artifact
        from docqa_tpu.engines.retrieve import FusedTieredRetriever

        ft = FusedTieredRetriever(encoder, tiered)
        ft.search_texts([q_texts[0]], k=10)  # compile
        t_ftier, _ = timed(
            lambda: ft.search_texts([q_texts[1]], k=10), n=5
        )
        DETAILS["ivf"] = {
            "recall_at_10": round(hits / max(total, 1), 4),
            "build_s": round(t_build, 1),
            "tiered_batch20_ms": round(t_tier * 1e3, 2),
            "exact_batch20_ms": round(t_exact20 * 1e3, 2),
            "tiered_batch1_ms": round(t_tier1 * 1e3, 2),
            "exact_batch1_ms": round(t_exact1 * 1e3, 2),
            "fused_tiered_query_ms": round(t_ftier * 1e3, 2),
        }
        del ft
        log(
            f"ivf: recall@10 {hits/max(total,1):.3f}, build {t_build:.1f}s, "
            f"batch-20 tiered {t_tier*1e3:.1f}ms vs exact "
            f"{t_exact20*1e3:.1f}ms; batch-1 tiered {t_tier1*1e3:.1f}ms "
            f"vs exact {t_exact1*1e3:.1f}ms"
        )
        del tiered
        gc.collect()
    except Exception as e:  # keep the headline alive
        log(f"ivf bench failed: {e!r}")
        DETAILS["ivf"] = {"error": repr(e)}
    flush_details()

    # ---- headline: e2e QA latency (solo requests) ---------------------------
    # The serving default is int8 weight-only (w8a16, models/quant.py):
    # decode is HBM-bandwidth bound, so halving the weight bytes read per
    # step is the single biggest latency lever, and the scheme's worst-case
    # relative weight error (<=1/254 per channel) is quality-neutral at
    # serving precision.  The bf16 engine is measured alongside for
    # comparability with round 1.
    def make_ask(engine):
        def ask(q: str) -> None:
            hits = retriever.search_texts([q], k=3)[0]
            ctx = "\n".join(
                f"[{h.metadata['doc_id']}] {h.metadata['source']}" for h in hits
            )
            prompt = f"Context:\n{ctx}\n\nQuestion: {q}\nAnswer:"
            engine.generate_texts([prompt], max_new_tokens=max_new)

        return ask

    def measure_e2e(engine, queries, tag):
        ask = make_ask(engine)
        for q in q_texts[:2]:  # compile prefill/decode at the served shapes
            ask(q)
        lat = []
        for q in queries:
            t0 = time.perf_counter()
            ask(q)
            lat.append((time.perf_counter() - t0) * 1000.0)
        p50 = float(np.percentile(lat, 50))
        p95 = float(np.percentile(lat, 95))
        log(f"{tag} e2e: p50 {p50:.1f}ms p95 {p95:.1f}ms ({max_new} new tokens)")
        return p50, p95

    def measure_decode(engine, key, tag):
        pb = param_bytes(engine.params)
        n_tok = 64 if not small else 8
        engine.generate_ids([[5, 9, 11]], max_new_tokens=n_tok)  # compile
        t_dec, _ = timed(
            lambda: engine.generate_ids([[5, 9, 11]], max_new_tokens=n_tok),
            n=3,
        )
        tok_s = n_tok / t_dec
        hbm_util = tok_s * pb / (V5E_HBM_GBPS * 1e9) if on_tpu else None
        DETAILS[key] = {
            "tokens_per_s": round(tok_s, 1),
            "param_bytes_gb": round(pb / 1e9, 2),
            "hbm_utilization": round(hbm_util, 3) if hbm_util else None,
        }
        log(
            f"{tag} decode ({pb/1e9:.1f}GB params): {tok_s:.0f} tok/s"
            + (f", HBM util {hbm_util:.0%}" if hbm_util else "")
        )

    # bf16 companion numbers (round-1 comparability)
    p50_bf16, p95_bf16 = measure_e2e(gen, q_texts[2:7], "bf16")
    DETAILS["qa_e2e_bf16"] = {
        "p50_ms": round(p50_bf16, 2),
        "p95_ms": round(p95_bf16, 2),
        "new_tokens": max_new,
        "decoder": f"{dec_cfg.hidden_dim}x{dec_cfg.num_layers}",
    }
    measure_decode(gen, "decode_1b", "config3a bf16")
    del gen
    gc.collect()

    # the served engine: same architecture, int8 weights
    import dataclasses

    gen = GenerateEngine(
        dataclasses.replace(dec_cfg, quantize_weights=True), mesh=mesh
    )
    dispatch_health("before_headline")
    p50, p95 = measure_e2e(gen, q_texts[2:], "headline (int8 serving)")
    DETAILS["qa_e2e"] = {
        "p50_ms": round(p50, 2),
        "p95_ms": round(p95, 2),
        "new_tokens": max_new,
        "decoder": f"{dec_cfg.hidden_dim}x{dec_cfg.num_layers}-int8",
    }
    DETAILS["headline_config"] = "qa_e2e"  # upgraded to 7B-int8 below
    measure_decode(gen, "decode_1b_int8", "config3a int8")

    # fused single-sync ask (engines/rag_fused.py): retrieval -> device-
    # side prompt pack -> decode, chained with no intermediate fetch —
    # the classic path above pays one extra sync for the chunk texts
    def measure_fused(engine, tag):
        from docqa_tpu.engines.rag_fused import FusedRAG
        from docqa_tpu.service.qa import QA_TEMPLATE

        rag = FusedRAG(encoder, store, engine, QA_TEMPLATE, k=3)
        rag.ask(q_texts[0], max_new_tokens=max_new)  # compile
        lats = []
        for q in q_texts[2 : 2 + n_queries]:
            t0 = time.perf_counter()
            rag.ask(q, max_new_tokens=max_new)
            lats.append((time.perf_counter() - t0) * 1e3)
        p50f = float(np.percentile(lats, 50))
        p95f = float(np.percentile(lats, 95))
        DETAILS[tag] = {
            "p50_ms": round(p50f, 2),
            "p95_ms": round(p95f, 2),
            "new_tokens": max_new,
        }
        log(f"{tag}: p50 {p50f:.1f}ms p95 {p95f:.1f}ms")
        return p50f, p95f

    try:
        measure_fused(gen, "qa_e2e_fused")
    except Exception as e:
        log(f"fused e2e failed: {e!r}")
        DETAILS["qa_e2e_fused"] = {"error": repr(e)[:300]}
    flush_details()

    # ---- config 5: sustained QPS through the continuous batcher -------------
    def run_load(engine, n_slots, chunk, n_req, cache_len):
        """One load measurement: n_req concurrent requests, max_new tokens
        each, through a ContinuousBatcher with the given knobs.  Returns
        (qps, wall_s, lat_ms) where lat_ms are per-request completion
        latencies (submit→done, measured by waiter threads so slow early
        results don't distort later ones)."""
        import threading as _threading

        from docqa_tpu.engines.serve import ContinuousBatcher

        b = ContinuousBatcher(
            engine, n_slots=n_slots, chunk=chunk, cache_len=cache_len
        )
        try:
            prompt_ids = [
                [7 + i % 13, 5, 9, 11, 3 + i % 7] for i in range(n_req)
            ]
            # warm: compile the batched admission prefill at the shapes the
            # loaded rounds hit (full-slot rounds) plus trickle shapes, and
            # the slot decode program
            for h in [
                b.submit_ids(p, max_new_tokens=4) for p in prompt_ids[:n_slots]
            ]:
                h.result()
            b.submit_ids(prompt_ids[0], max_new_tokens=max_new).result()
            lat_ms = [0.0] * n_req
            waiters = []
            t0 = time.perf_counter()

            def wait_one(idx, handle):
                handle.result()
                lat_ms[idx] = (time.perf_counter() - t0) * 1e3

            for i, p in enumerate(prompt_ids):
                h = b.submit_ids(p, max_new_tokens=max_new)
                w = _threading.Thread(target=wait_one, args=(i, h))
                w.start()
                waiters.append(w)
            for w in waiters:
                w.join()
            wall = time.perf_counter() - t0
        finally:
            # stop on EVERY path: a leaked batcher thread holds the engine
            b.stop()
            del b
            gc.collect()
        return n_req / wall, wall, lat_ms

    def sweep_load(engine, n_req, cache_len, grid):
        """A REAL knob grid (VERDICT r3 item 2): measure every (n_slots,
        chunk) combo in ``grid`` — slots and chunk trade per-request latency
        for aggregate throughput, and the served config should be the
        measured winner, not a guess.  Stops early only once the target is
        comfortably beaten (QPS ≥ 20: past that the remaining bench budget
        buys more than another grid point does).  Returns the rag_load
        DETAILS dict; the speculative_k stage runs at the winner after."""
        attempts = []
        qps, wall, lat = run_load(engine, *grid[0], n_req, cache_len)
        attempts.append(
            {"n_slots": grid[0][0], "chunk": grid[0][1], "qps": round(qps, 2)}
        )
        if not small:
            for ns, ch in grid[1:]:
                if qps >= 20:
                    attempts.append({"skipped_past": f"({ns},{ch})"})
                    break
                try:
                    q2, w2, l2 = run_load(engine, ns, ch, n_req, cache_len)
                except Exception as e:
                    log(f"load sweep ({ns},{ch}) failed: {e!r}")
                    continue
                attempts.append(
                    {"n_slots": ns, "chunk": ch, "qps": round(q2, 2)}
                )
                if q2 > qps:
                    qps, wall, lat = q2, w2, l2
        best = max(
            (a for a in attempts if "qps" in a), key=lambda a: a["qps"]
        )
        return {
            "requests": n_req,
            "wall_s": round(wall, 2),
            "sustained_qps": round(qps, 2),
            "qps_target": 16,
            # BASELINE config 5 asks for per-request latency under load,
            # not just aggregate QPS (winner's distribution)
            "request_p50_ms": round(float(np.percentile(lat, 50)), 1),
            "request_p95_ms": round(float(np.percentile(lat, 95)), 1),
            "best_knobs": {"n_slots": best["n_slots"], "chunk": best["chunk"]},
            "attempts": attempts,
        }

    try:
        n_req = 64 if not small else 8
        cache_len = 1024 if not small else 256
        # stage 1 of the grid: n_slots x chunk (16,32) first — the prior
        # rounds' serving default — then the rest in rising-cost order
        DETAILS["rag_load"] = sweep_load(
            gen,
            n_req,
            cache_len,
            ((16, 32), (32, 32), (16, 64), (32, 64), (16, 16), (32, 16)),
        )
        if not small and DETAILS["rag_load"]["sustained_qps"] < 20:
            # stage 2 of the grid (VERDICT r2 item 2 / r3 item 2):
            # speculative_k at the stage-1 winner — each batcher chunk
            # verifies spec_k draft tokens per slot in one weight read,
            # raising aggregate tokens/read.  Own try: a failure here must
            # not wipe the measured sweep above.
            try:
                bk = DETAILS["rag_load"]["best_knobs"]
                for spec_k in (4, 8):
                    gen_spec = GenerateEngine(
                        dataclasses.replace(dec_cfg, quantize_weights=True),
                        GenerateConfig(speculative_k=spec_k),
                        mesh=mesh,
                        params=gen.params,
                    )
                    try:
                        qs, ws, ls = run_load(
                            gen_spec, bk["n_slots"], bk["chunk"], n_req,
                            cache_len,
                        )
                    finally:
                        del gen_spec
                        gc.collect()
                    DETAILS["rag_load"]["attempts"].append(
                        {**bk, "speculative_k": spec_k, "qps": round(qs, 2)}
                    )
                    if qs > DETAILS["rag_load"]["sustained_qps"]:
                        DETAILS["rag_load"].update(
                            sustained_qps=round(qs, 2),
                            wall_s=round(ws, 2),
                            request_p50_ms=round(
                                float(np.percentile(ls, 50)), 1
                            ),
                            request_p95_ms=round(
                                float(np.percentile(ls, 95)), 1
                            ),
                            best_knobs={**bk, "speculative_k": spec_k},
                        )
            except Exception as e:
                log(f"config5 speculation attempt failed: {e!r}")
                DETAILS["rag_load"]["speculation_error"] = repr(e)[:200]
        log(f"config5 load: {DETAILS['rag_load']}")
    except Exception as e:
        log(f"qps bench failed: {e!r}")
        DETAILS["rag_load"] = {"error": repr(e)}
    flush_details()

    # ---- config 4: summarizer, 5 retrieved chunks ---------------------------
    summ = None
    try:
        from docqa_tpu.engines.summarize import SummarizeEngine

        summ = SummarizeEngine(gen, SummarizerConfig())
        docs = [
            (f"doc{i}", f"Patient note {i}: " + "stable vitals observed. " * 40)
            for i in range(5)
        ]
        summ.summarize_patient("p1", docs, max_tokens=32 if small else 128)
        t_summ, _ = timed(
            lambda: summ.summarize_patient(
                "p1", docs, max_tokens=32 if small else 128
            )
        )
        DETAILS["summarize"] = {"five_chunk_ms": round(t_summ * 1e3, 1)}
        log(f"config4 summarize (5 chunks): {t_summ*1e3:.0f}ms")
    except Exception as e:
        log(f"summarize bench failed: {e!r}")
        DETAILS["summarize"] = {"error": repr(e)}

    # ---- config 4b: the dedicated BART-class encoder-decoder backend --------
    # (the architecture BASELINE config 4 actually names; bart-large-cnn
    # shape, ~0.8 GB bf16 — raw-source summarization, no instruction prompt)
    try:
        from docqa_tpu.config import Seq2SeqConfig
        from docqa_tpu.engines.seq2seq import Seq2SeqEngine

        import dataclasses as _dc

        # greedy for the timed run: the beam-4 program XLA-compiles for
        # minutes at bart-large depth on this host and measures the same
        # bandwidth-bound forward; beam decode is covered by tests
        s2s_cfg = (
            Seq2SeqConfig()
            if small
            else _dc.replace(
                Seq2SeqConfig.bart_large_cnn(),
                # route through the plain greedy program: the generation
                # constraints all live in the beam program, whose compile
                # at bart-large depth runs minutes on this host
                num_beams=1,
                min_length=0,
                no_repeat_ngram=0,
            )
        )
        s2s = Seq2SeqEngine(s2s_cfg)
        summ2 = SummarizeEngine(
            s2s,
            SummarizerConfig(max_input_tokens=s2s_cfg.max_src_len),
            instruction_prompts=False,
        )
        summ2.summarize_patient("p1", docs, max_tokens=16 if small else 128)
        t_s2s, _ = timed(
            lambda: summ2.summarize_patient(
                "p1", docs, max_tokens=16 if small else 128
            )
        )
        DETAILS["summarize_seq2seq"] = {
            "five_chunk_ms": round(t_s2s * 1e3, 1),
            "model": f"bart-class {s2s_cfg.d_model}x"
            f"{s2s_cfg.enc_layers}+{s2s_cfg.dec_layers}",
            "decode": "greedy",
        }
        log(f"config4b seq2seq summarize (5 chunks): {t_s2s*1e3:.0f}ms")
        del s2s, summ2
        gc.collect()
        if not small:
            def run_beam_late():
                # beam-4 with the full generation constraints — BASELINE
                # config 4 names bart-large-cnn whose published decode IS
                # beam.  Deferred: the beam program's XLA compile at this
                # depth is the risk (minutes), not its runtime — it must
                # not sit between the driver and the headline.
                try:
                    s2s_beam = Seq2SeqEngine(Seq2SeqConfig.bart_large_cnn())
                    summ_b = SummarizeEngine(
                        s2s_beam,
                        SummarizerConfig(
                            max_input_tokens=s2s_cfg.max_src_len
                        ),
                        instruction_prompts=False,
                    )
                    t0 = time.perf_counter()
                    summ_b.summarize_patient("p1", docs, max_tokens=128)
                    compile_s = time.perf_counter() - t0
                    t_beam, _ = timed(
                        lambda: summ_b.summarize_patient(
                            "p1", docs, max_tokens=128
                        )
                    )
                    DETAILS["summarize_seq2seq_beam"] = {
                        "five_chunk_ms": round(t_beam * 1e3, 1),
                        "compile_s": round(compile_s, 1),
                        "num_beams": (
                            Seq2SeqConfig.bart_large_cnn().num_beams
                        ),
                    }
                    log(
                        f"config4b beam summarize (5 chunks): "
                        f"{t_beam*1e3:.0f}ms (compile {compile_s:.0f}s)"
                    )
                except Exception as e:
                    log(f"beam summarize bench failed: {e!r}")
                    DETAILS["summarize_seq2seq_beam"] = {
                        "error": repr(e)[:300]
                    }

            late_sections.append(run_beam_late)
    except Exception as e:
        log(f"seq2seq summarize bench failed: {e!r}")
        DETAILS["summarize_seq2seq"] = {"error": repr(e)[:300]}
    flush_details()

    # ---- config 2: deid NER throughput, batch = 32 --------------------------
    try:
        from docqa_tpu.deid.engine import DeidEngine

        _ner_cache = os.path.join(
            os.path.expanduser("~"), ".cache", "docqa_tpu", "ner.npz"
        )
        if small:
            # random-init weights: identical FLOPs/memory to trained, and
            # the tagger architecture is what config 2 measures
            deid = DeidEngine(NERConfig(), use_ner_model=True)
        else:
            # trained weights via the cache: realistic weights for the
            # throughput number, reused by the late quality section and
            # across bench reruns; load_or_train runs any needed training
            # in a CHILD process so its minutes of step loops and sync
            # churn never sit inside this process between the driver and
            # the 7B headline
            os.makedirs(os.path.dirname(_ner_cache), exist_ok=True)
            deid = DeidEngine.trained(NERConfig(), params_path=_ner_cache)
        docs32 = [
            f"Patient {i} was admitted on 2024-03-{1 + i % 27:02d} with "
            "chest pain. " + "History reviewed with the care team. " * 20
            for i in range(32)
        ]
        deid.deidentify_batch(docs32)  # compile
        t_deid, _ = timed(lambda: deid.deidentify_batch(docs32), n=3)
        DETAILS["deid"] = {
            "batch32_ms": round(t_deid * 1e3, 1),
            "docs_per_s": round(32 / t_deid, 1),
        }
        log(f"config2 deid: batch-32 in {t_deid*1e3:.0f}ms = {32/t_deid:.0f} docs/s")
        del deid
        gc.collect()
        if not small:
            def run_deid_quality_late():
                # quality, not just speed: train the real tagger and
                # score it on the HAND-WRITTEN eval set (deid/evalset.py
                # — sentences disjoint from the training generator's
                # templates, so this measures generalization, not
                # memorization).  Deferred: training takes minutes and
                # must not sit between the driver and the headline.
                try:
                    from docqa_tpu.deid.evalset import evaluate_deid

                    t0 = time.perf_counter()
                    deid_trained = DeidEngine.trained(
                        NERConfig(), params_path=_ner_cache
                    )
                    ev = evaluate_deid(deid_trained)
                    # record the headline quality numbers BEFORE the sweep:
                    # a sweep failure must not discard minutes of training
                    # plus a successful base eval
                    DETAILS["deid"].update(
                        {
                            "train_s": round(time.perf_counter() - t0, 1),
                            "f1": ev["entity_f1"],
                            "char_f1": ev["char_f1"],
                            "span_recall_any": ev["span_recall_any"],
                            "eval": ev,
                        }
                    )
                    # the softmax acceptance threshold is a no-retrain
                    # precision/recall lever; each eval is sub-second with
                    # the tagger in memory, so sweep it and report the
                    # operating curve alongside the served default
                    th_sweep = {}
                    served_th = deid_trained.ner_threshold
                    try:
                        for th in (0.3, 0.5, 0.65, 0.8, 0.9):
                            deid_trained.ner_threshold = th
                            e = evaluate_deid(deid_trained)
                            th_sweep[str(th)] = {
                                "entity_f1": e["entity_f1"],
                                "char_f1": e["char_f1"],
                            }
                    except Exception as e:  # keep the base metrics
                        th_sweep["error"] = repr(e)[:200]
                    finally:
                        deid_trained.ner_threshold = served_th
                    DETAILS["deid"]["threshold_sweep"] = th_sweep
                    log(
                        f"config2 deid quality (handwritten eval): entity "
                        f"F1 {ev['entity_f1']}, char F1 {ev['char_f1']}, "
                        f"span recall {ev['span_recall_any']}"
                    )
                    del deid_trained
                    gc.collect()
                except Exception as e:
                    log(f"deid quality eval failed: {e!r}")
                    DETAILS["deid"]["eval_error"] = repr(e)[:300]

            late_sections.append(run_deid_quality_late)
    except Exception as e:
        log(f"deid bench failed: {e!r}")
        DETAILS["deid"] = {"error": repr(e)}
    flush_details()

    # ---- configs 3c/5b/3b: Mistral-7B-class on one chip ---------------------
    if not small:
        # free the 1.1B engines — including `summ`, which holds one as
        # .generator (a leaked ref here would make the 7B verdict measure
        # under ~2 GB of false memory pressure).  The 1M store (~0.8 GB)
        # STAYS resident: the headline configuration is 7B-int8 e2e over it
        # (the model class BASELINE config 3 actually names).
        summ = None  # noqa: F841
        del gen
        gc.collect()

        # ---- config 3c: 7B int8 weights (w8a16) — the serving path that
        # fits one v5e chip (~7.2 GB tree, half the bytes per decode step;
        # models/quant.py)
        try:
            from docqa_tpu.models.quant import init_quantized_decoder_params

            cfg7 = DecoderConfig.mistral_7b()
            # HOST init deliberately: the device-side jax.random init
            # sequence leaves the tunneled client in its degraded mode
            # (docs/PERF.md §1, ~70 ms on EVERY later dispatch) and the
            # headline e2e + 5b load both run after this point in this
            # process.  The one-time cost is drawing + transferring the
            # 7.2 GB tree — the decode-only bf16 attempt (config 3b, runs
            # last) keeps device init because nothing measured after it.
            params8 = init_quantized_decoder_params(
                jax.random.PRNGKey(0), cfg7, host_init=True, host_seed=0
            )
            pb8 = param_bytes(params8)
            gen8 = GenerateEngine(
                cfg7,
                GenerateConfig(max_new_tokens=64, prefill_buckets=(128,)),
                params=params8,
            )
            gen8.generate_ids([[5, 9, 11]], max_new_tokens=64)  # compile
            t8, _ = timed(
                lambda: gen8.generate_ids([[5, 9, 11]], max_new_tokens=64), n=3
            )
            tok8 = 64 / t8
            util8 = tok8 * pb8 / (V5E_HBM_GBPS * 1e9) if on_tpu else None
            DETAILS["decode_7b_int8"] = {
                "tokens_per_s": round(tok8, 1),
                "param_bytes_gb": round(pb8 / 1e9, 2),
                "hbm_utilization": round(util8, 3) if util8 else None,
            }
            log(
                f"config3c Mistral-7B-class int8 ({pb8/1e9:.1f}GB): "
                f"{tok8:.1f} tok/s"
                + (f", HBM util {util8:.0%}" if util8 else "")
            )

            # ---- HEADLINE: 7B-int8 e2e QA over the 1M store, speculation
            # swept.  Prompt-lookup speculation is output-exact (greedy
            # match or it falls back), so the best speculative_k is purely
            # a latency decision — measure, don't guess.
            try:
                e2e_attempts = []
                best = None
                for spec_k in (0, 4, 8):
                    eng_k = (
                        gen8
                        if spec_k == 0
                        else GenerateEngine(
                            cfg7,
                            GenerateConfig(
                                max_new_tokens=64,
                                prefill_buckets=(128,),
                                speculative_k=spec_k,
                            ),
                            params=params8,
                        )
                    )
                    try:
                        p50k, p95k = measure_e2e(
                            eng_k,
                            q_texts[2 : 2 + n_e2e_7b],
                            f"7B-int8 spec_k={spec_k}",
                        )
                    finally:
                        # release on the error path too: a leaked spec
                        # engine would hold the 7B tree and starve the
                        # bf16 attempt below of HBM it needs
                        if eng_k is not gen8:
                            del eng_k
                            gc.collect()
                    e2e_attempts.append(
                        {
                            "speculative_k": spec_k,
                            "p50_ms": round(p50k, 2),
                            "p95_ms": round(p95k, 2),
                        }
                    )
                    if best is None or p50k < best[1]:
                        best = (spec_k, p50k, p95k)
                DETAILS["qa_e2e_7b_int8"] = {
                    "p50_ms": round(best[1], 2),
                    "p95_ms": round(best[2], 2),
                    "new_tokens": max_new,
                    "decoder": "mistral-7b-class-int8",
                    "speculative_k": best[0],
                    "attempts": e2e_attempts,
                }
                # this is the number the summary line reports — the 1.1B
                # figures above stay in DETAILS for round-over-round
                # comparability
                p50 = best[1]
                DETAILS["headline_config"] = "qa_e2e_7b_int8"
                log(
                    f"HEADLINE 7B-int8 e2e: p50 {best[1]:.1f}ms "
                    f"p95 {best[2]:.1f}ms (spec_k={best[0]})"
                )
                # fused single-sync variant at the winning spec_k — takes
                # the headline only if its measured p50 actually wins
                try:
                    eng_f = GenerateEngine(
                        cfg7,
                        GenerateConfig(
                            max_new_tokens=64,
                            prefill_buckets=(512, 1024),
                            speculative_k=best[0],
                        ),
                        params=params8,
                    )
                    try:
                        p50f, _ = measure_fused(
                            eng_f, "qa_e2e_7b_int8_fused"
                        )
                    finally:
                        del eng_f
                        gc.collect()
                    if p50f < p50:
                        p50 = p50f
                        DETAILS["headline_config"] = "qa_e2e_7b_int8_fused"
                        log(
                            f"HEADLINE upgraded to fused 7B-int8 e2e: "
                            f"p50 {p50f:.1f}ms"
                        )
                except Exception as e:
                    log(f"7B fused e2e failed: {e!r}")
                    DETAILS["qa_e2e_7b_int8_fused"] = {
                        "error": repr(e)[:300]
                    }
            except Exception as e:
                log(f"7B e2e headline failed (1.1B number stands): {e!r}")
                DETAILS["qa_e2e_7b_int8"] = {"error": repr(e)[:300]}

            # ---- config 5b: 7B-class under load — BASELINE config 5's
            # generator class through the batcher.  The slots share each
            # int8 weight read, so aggregate throughput approaches
            # slots/step-time even at 7B on one chip.
            try:
                from docqa_tpu.runtime.metrics import (
                    DEFAULT_REGISTRY as _REG,
                )

                # delta-window the global histogram: config 5's 1.1B runs
                # already observed into it, and the lifetime mean would
                # blend models
                hist = _REG.histogram("serve_tokens_per_chunk")
                count0 = hist.count
                sum0 = (hist.mean * count0) if count0 else 0.0
                # serve with the e2e sweep's best speculative_k: in the
                # batcher each chunk verifies spec_k draft tokens per slot
                # in ONE weight read, so speculation raises load
                # throughput, not just solo latency
                best_k = DETAILS.get("qa_e2e_7b_int8", {}).get(
                    "speculative_k", 0
                )
                load_engine = (
                    GenerateEngine(
                        cfg7,
                        GenerateConfig(
                            max_new_tokens=64,
                            prefill_buckets=(128,),
                            speculative_k=best_k,
                        ),
                        params=params8,
                    )
                    if best_k
                    else gen8
                )
                try:
                    # (32, 32) first — the r04 full-bench winner (9.26 QPS
                    # vs 9.13 at (32,16), docs/bench_r04_insession.json);
                    # the two small-chunk points stay in the grid because
                    # they trade within noise run-to-run
                    DETAILS["rag_load_7b_int8"] = sweep_load(
                        load_engine, 32, 512, ((32, 32), (32, 16), (16, 64))
                    )
                finally:
                    # release on the error path too: a leaked 7B engine
                    # would starve the bf16 attempt below of HBM
                    if load_engine is not gen8:
                        del load_engine
                        gc.collect()
                DETAILS["rag_load_7b_int8"]["speculative_k"] = best_k
                d_count = hist.count - count0
                DETAILS["rag_load_7b_int8"]["serve_tokens_per_chunk_mean"] = (
                    round((hist.mean * hist.count - sum0) / d_count, 2)
                    if d_count > 0
                    else None
                )
                log(f"config5b 7B-int8 load: {DETAILS['rag_load_7b_int8']}")
            except Exception as e:
                log(f"7B int8 load bench failed: {e!r}")
                DETAILS["rag_load_7b_int8"] = {"error": repr(e)[:300]}
            dispatch_health("after_7b_sections")
            del gen8, params8
            gc.collect()
        except Exception as e:
            log(f"config3c 7B int8 attempt failed: {e!r}")
            DETAILS["decode_7b_int8"] = {"error": repr(e)[:500]}
        flush_details()

        # ---- config 3d: 7B grouped-int4 (w4a16, ~3.6 GB — the q4 class
        # the reference's Ollama runtime actually served).  Decode reads
        # half of int8's bytes, so bandwidth-bound tok/s should ~double;
        # if its e2e beats the int8 headline, it takes the headline.
        gen4 = params4 = None
        try:
            cfg7 = DecoderConfig.mistral_7b()
            # Capability gate FIRST (r04 post-mortem): on the tunneled
            # axon backend, lowering an S4 program fails client-side, and
            # the subsequent full-program compile attempt came back
            # UNIMPLEMENTED and left the client in a state where EVERY
            # later dispatch failed — killing config 3b, the beam bench,
            # and the deid quality eval of that run.  probe_int4_support
            # proves the dtype end-to-end on a toy program (which fails
            # fast WITHOUT poisoning the client — verified in-session)
            # before anything allocates a multi-GB tree or compiles an
            # int4-shaped program.
            import jax.numpy as _jnp

            from docqa_tpu.models.quant import probe_int4_support

            _int4_ok, _int4_why = probe_int4_support()
            if not _int4_ok:
                raise RuntimeError(
                    "backend cannot execute int4 programs "
                    f"(capability probe: {_int4_why})"
                )
            # fusion probe BEFORE allocating the tree: if the backend
            # materializes the dequantized bf16 weight instead of fusing
            # the grouped dequant into the dot, the temp allocation shows
            # it here (one mlp weight = 117 MB bf16) and the section's
            # tok/s will confirm — record both, never assume
            try:

                from docqa_tpu.models.decoder import _qmatmul

                _g = 128
                _probe_p = {
                    "w": _jnp.zeros(
                        (cfg7.mlp_dim // _g, _g, cfg7.hidden_dim),
                        _jnp.int4,
                    ),
                    "w__scale": _jnp.zeros(
                        (cfg7.mlp_dim // _g, cfg7.hidden_dim), _jnp.float32
                    ),
                }
                _x = _jnp.zeros((1, cfg7.mlp_dim), _jnp.bfloat16)
                _ma = (
                    jax.jit(
                        lambda x, p: _qmatmul(x, p, "w", _jnp.bfloat16)
                    )
                    .lower(_x, _probe_p)
                    .compile()
                    .memory_analysis()
                )
                DETAILS["int4_fusion_probe"] = {
                    "temp_bytes": int(_ma.temp_size_in_bytes),
                    "materialized_tree_bytes": cfg7.mlp_dim
                    * cfg7.hidden_dim
                    * 2,
                }
                log(f"int4 fusion probe: {DETAILS['int4_fusion_probe']}")
                del _probe_p, _x
            except Exception as e:
                log(f"int4 fusion probe failed: {e!r}")
            params4 = init_quantized_decoder_params(
                jax.random.PRNGKey(0), cfg7, host_init=True, bits=4,
                host_seed=0,
            )
            pb4 = param_bytes(params4)  # NOTE: host itemsize counts int4
            # as 1 byte; the packed on-device tree is half this
            gen4 = GenerateEngine(
                cfg7,
                GenerateConfig(max_new_tokens=64, prefill_buckets=(128,)),
                params=params4,
            )
            gen4.generate_ids([[5, 9, 11]], max_new_tokens=64)  # compile
            t4, _ = timed(
                lambda: gen4.generate_ids([[5, 9, 11]], max_new_tokens=64),
                n=3,
            )
            tok4 = 64 / t4
            pb4_packed = pb4 - sum(
                int(np.prod(v.shape)) // 2
                for v in params4.values()
                if str(v.dtype) == "int4"
            )
            util4 = (
                tok4 * pb4_packed / (V5E_HBM_GBPS * 1e9) if on_tpu else None
            )
            DETAILS["decode_7b_int4"] = {
                "tokens_per_s": round(tok4, 1),
                "param_bytes_gb": round(pb4_packed / 1e9, 2),
                "hbm_utilization": round(util4, 3) if util4 else None,
            }
            log(
                f"config3d Mistral-7B-class int4 ({pb4_packed/1e9:.1f}GB "
                f"packed): {tok4:.1f} tok/s"
                + (f", HBM util {util4:.0%}" if util4 else "")
            )
            try:
                best_k4 = DETAILS.get("qa_e2e_7b_int8", {}).get(
                    "speculative_k", 0
                )
                eng4 = (
                    gen4
                    if not best_k4
                    else GenerateEngine(
                        cfg7,
                        GenerateConfig(
                            max_new_tokens=64,
                            prefill_buckets=(128,),
                            speculative_k=best_k4,
                        ),
                        params=params4,
                    )
                )
                try:
                    p50_4, p95_4 = measure_e2e(
                        eng4,
                        q_texts[2 : 2 + n_e2e_7b],
                        f"7B-int4 spec_k={best_k4}",
                    )
                finally:
                    if eng4 is not gen4:
                        del eng4
                        gc.collect()
                DETAILS["qa_e2e_7b_int4"] = {
                    "p50_ms": round(p50_4, 2),
                    "p95_ms": round(p95_4, 2),
                    "new_tokens": max_new,
                    "decoder": "mistral-7b-class-int4-g128",
                    "speculative_k": best_k4,
                }
                if p50_4 < p50:
                    p50 = p50_4
                    DETAILS["headline_config"] = "qa_e2e_7b_int4"
                    log(
                        f"HEADLINE upgraded to 7B-int4 e2e: p50 "
                        f"{p50_4:.1f}ms"
                    )
            except Exception as e:
                log(f"7B int4 e2e failed: {e!r}")
                DETAILS["qa_e2e_7b_int4"] = {"error": repr(e)[:300]}
        except Exception as e:
            log(f"config3d 7B int4 attempt failed: {e!r}")
            DETAILS["decode_7b_int4"] = {"error": repr(e)[:500]}
        finally:
            # free on EVERY path: a leaked int4 tree would make config
            # 3b's 14.5 GB bf16 attempt OOM for the wrong reason
            del gen4, params4
            gc.collect()
            flush_details()

        # ---- config 3b: the same 7B in bf16 (14.5 GB) — needs ALL the
        # HBM, so the store/encoder go first; runs last for that reason
        del store, encoder, retriever
        gc.collect()
        try:
            import jax.numpy as jnp

            from docqa_tpu.models.decoder import init_decoder_params

            cfg7 = DecoderConfig.mistral_7b()
            # device-side init deliberately: host init would draw + transfer
            # 14.5 GB through the tunnel (minutes), while the dispatch
            # degradation it avoids costs ~70 ms on each of the THREE timed
            # decode calls this section makes — serving engines host-init,
            # one-shot measurements don't need to
            params7 = init_decoder_params(
                jax.random.PRNGKey(0), cfg7, param_dtype=jnp.bfloat16
            )
            pb7 = param_bytes(params7)
            gen7 = GenerateEngine(
                cfg7,
                GenerateConfig(max_new_tokens=64, prefill_buckets=(128,)),
                params=params7,
            )
            gen7.generate_ids([[5, 9, 11]], max_new_tokens=64)  # compile
            t7, _ = timed(
                lambda: gen7.generate_ids([[5, 9, 11]], max_new_tokens=64), n=3
            )
            tok7 = 64 / t7
            util7 = tok7 * pb7 / (V5E_HBM_GBPS * 1e9) if on_tpu else None
            DETAILS["decode_7b"] = {
                "tokens_per_s": round(tok7, 1),
                "param_bytes_gb": round(pb7 / 1e9, 2),
                "hbm_utilization": round(util7, 3) if util7 else None,
            }
            log(
                f"config3b Mistral-7B-class bf16 ({pb7/1e9:.1f}GB): "
                f"{tok7:.1f} tok/s"
                + (f", HBM util {util7:.0%}" if util7 else "")
            )
            del gen7, params7
            gc.collect()
        except Exception as e:
            # one v5e chip has 16 GB HBM; a 14.5 GB weight tree may not
            # leave room — record the honest outcome either way
            log(f"config3b 7B bf16 attempt failed: {e!r}")
            DETAILS["decode_7b"] = {"error": repr(e)[:500]}

    # ---- emit ---------------------------------------------------------------
    # A CPU fallback run must be UNMISTAKABLE in the one line the driver
    # parses: distinct metric name AND an explicit degraded flag, so no
    # artifact comparison can mistake a smoke run for a TPU measurement
    # (the r02 artifact was misleading exactly this way).  The line prints
    # BEFORE the deferred slow sections (NER training, beam compile): a
    # driver-side timeout during those must not cost the round its
    # headline number.
    degraded = not on_tpu
    DETAILS["degraded"] = degraded
    flush_details()
    summary = {
        "metric": "qa_e2e_p50_ms" + ("_cpu_smoke" if degraded else ""),
        "value": round(p50, 2),
        "unit": "ms",
        "vs_baseline": round(1000.0 / p50, 3),
    }
    if degraded:
        summary["degraded"] = True
    print(json.dumps(summary), flush=True)

    for section in late_sections:
        section()
        flush_details()
    log(f"details: {json.dumps(DETAILS)}")


if __name__ == "__main__":
    if os.environ.get("DOCQA_BENCH_INNER") == "1":
        main()
    else:
        sys.exit(_run_with_fallback())
