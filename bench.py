"""End-to-end QA latency benchmark (the driver runs this on real TPU).

Measures the north-star metric from BASELINE.md: end-to-end QA latency —
  tokenize + encode the question (MiniLM-class jit encoder)
  → exact cosine top-k over an HBM-resident corpus (1M chunks on TPU)
  → RAG prompt assembly
  → decoder LM generation with KV cache (64 new tokens) on-device.

The reference publishes no numbers (BASELINE.md: "measured, not inherited");
the north-star target is <1 s p50 on TPU.  ``vs_baseline`` is therefore
reported against that 1000 ms target: vs_baseline = 1000 / p50_ms (>1 means
the target is beaten).

Prints exactly one JSON line:
  {"metric": "qa_e2e_p50_ms", "value": p50, "unit": "ms", "vs_baseline": r}
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np


def main() -> None:
    import jax

    backend = jax.default_backend()
    on_tpu = backend == "tpu"
    small = (not on_tpu) or os.environ.get("DOCQA_BENCH_SMALL") == "1"

    from docqa_tpu.config import DecoderConfig, EncoderConfig, StoreConfig
    from docqa_tpu.engines.encoder import EncoderEngine
    from docqa_tpu.engines.generate import GenerateEngine
    from docqa_tpu.index.store import VectorStore
    from docqa_tpu.runtime.mesh import make_mesh

    n_chunks = 20_000 if small else 1_000_000
    max_new = 16 if small else 64
    n_queries = 5 if small else 20
    dec_cfg = (
        DecoderConfig()  # smoke size
        if small
        else DecoderConfig(  # ~1.1B-param class, fits one chip in f32
            vocab_size=32000,
            hidden_dim=2048,
            num_layers=16,
            num_heads=16,
            num_kv_heads=8,
            head_dim=128,
            mlp_dim=5632,
            max_seq_len=4096,
        )
    )

    mesh = make_mesh() if jax.device_count() > 1 else None

    encoder = EncoderEngine(EncoderConfig(), mesh=mesh)
    store = VectorStore(
        StoreConfig(shard_capacity=max(n_chunks, 16384)), mesh=mesh
    )
    rng = np.random.default_rng(0)
    block = 131_072
    meta_block = lambda s, n: [  # noqa: E731
        {"doc_id": f"d{i}", "source": f"chunk {i}", "type": "kb"}
        for i in range(s, s + n)
    ]
    for start in range(0, n_chunks, block):
        n = min(block, n_chunks - start)
        vecs = rng.standard_normal((n, 384)).astype(np.float32)
        store.add(vecs, meta_block(start, n))

    gen = GenerateEngine(dec_cfg, mesh=mesh)

    questions = [
        f"What formula treats syndrome {i} with highest score and why?"
        for i in range(n_queries + 2)
    ]

    def ask(q: str) -> None:
        emb = encoder.encode_texts([q])
        hits = store.search(emb, k=3)[0]
        ctx = "\n".join(f"[{h.metadata['doc_id']}] {h.metadata['source']}" for h in hits)
        prompt = f"Context:\n{ctx}\n\nQuestion: {q}\nAnswer:"
        gen.generate_texts([prompt], max_new_tokens=max_new)

    # warmup: compile encoder/search/prefill/decode programs
    for q in questions[:2]:
        ask(q)

    lat = []
    for q in questions[2:]:
        t0 = time.perf_counter()
        ask(q)
        lat.append((time.perf_counter() - t0) * 1000.0)

    p50 = float(np.percentile(lat, 50))
    p95 = float(np.percentile(lat, 95))
    print(
        f"# backend={backend} chunks={n_chunks} decoder={dec_cfg.hidden_dim}x"
        f"{dec_cfg.num_layers} new_tokens={max_new} p50={p50:.1f}ms p95={p95:.1f}ms",
        file=sys.stderr,
    )
    print(
        json.dumps(
            {
                "metric": "qa_e2e_p50_ms",
                "value": round(p50, 2),
                "unit": "ms",
                "vs_baseline": round(1000.0 / p50, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
