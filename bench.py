"""Benchmark suite: the full BASELINE.md config matrix on real TPU.

Headline (the driver contract — exactly ONE JSON line on stdout):
  {"metric": "qa_e2e_p50_ms", "value": p50, "unit": "ms", "vs_baseline": r}
measuring the north-star metric — end-to-end QA latency over a 1M-chunk
HBM-resident corpus, target <1 s p50 (the reference publishes no numbers,
BASELINE.md: "measured, not inherited"; vs_baseline = 1000 / p50_ms).

HEADLINE-FIRST ordering (VERDICT r4 item 1): the run drives straight to
the headline configuration — corpus ingest -> fused retriever ->
7B-int8 e2e at the known-best speculation — and PRINTS the JSON line the
moment it is measured (~8-10 min in).  Everything else runs AFTER the
line, each section gated by a wall-clock budget
(``DOCQA_BENCH_BUDGET_S``, default 1050 s) so the process always exits
cleanly inside the driver window; skipped sections are recorded under
``DETAILS["skipped"]`` with the reason.

Post-headline sections (stderr + ``bench_details.json``):

  1. retrieval: exact top-k latency at 1M chunks, encode-only, and the
     fused one-dispatch text->top-k path (measured pre-headline — it is
     on the headline path anyway)
  2. deid: NER PHI tagging throughput, batch = 32 docs (+ the trained-
     tagger quality eval on the dev/test split evalset, late)
  3. generator: greedy decode tokens/s + HBM-bandwidth utilization for
     the 7B class (int8 serving, int4 if the backend can lower it, bf16
     if HBM allows) and the 1.1B class in bf16 AND int8
  4. summarizer: 5-chunk patient summary latency on the decoder backend
     and on the dedicated BART-class encoder-decoder
  5. full RAG under load: closed-loop sustained QPS through the
     continuous batcher (target 16) AND a fixed-arrival OPEN-loop run at
     exactly QPS 16 reporting request p50/p95 + queue depth — the
     latency-under-target-load number BASELINE's metric names
     (VERDICT r4 item 3)

Corpus vectors are drawn from a 2000-center mixture (embedding-like
cluster structure) so the IVF recall measurement means something —
uniform random vectors are IVF's degenerate worst case and nothing like
real sentence embeddings.  Chunk TEXTS (and the token sidecar) come from
a realistic clinical-sentence pool, 60-120 generator tokens per chunk,
so the fused-vs-classic A/B carries equal context on both paths
(VERDICT r4 item 6 — the r04 A/B compared 2-token sources against
100-token sidecar rows and was rightly ruled invalid).
"""

from __future__ import annotations

import gc
import json
import os
import sys
import time
from typing import Optional, Tuple

import numpy as np

DETAILS: dict = {}
V5E_HBM_GBPS = 819.0  # v5e chip peak HBM bandwidth


def log(msg: str) -> None:
    print(f"# {msg}", file=sys.stderr, flush=True)


def flush_details() -> None:
    """Write bench_details.json NOW — called after every section so a
    driver-side timeout mid-run still leaves every completed measurement
    on disk."""
    try:
        with open(
            os.path.join(
                os.path.dirname(os.path.abspath(__file__)),
                "bench_details.json",
            ),
            "w",
        ) as f:
            json.dump(DETAILS, f, indent=2)
    except Exception as e:
        log(f"details write failed: {e!r}")


def timed(fn, n=1):
    t0 = time.perf_counter()
    for _ in range(n):
        out = fn()
    return (time.perf_counter() - t0) / n, out


def _unit(x):
    return x / np.linalg.norm(x, axis=-1, keepdims=True)


def make_centers(rng, n_centers, dim):
    """Hierarchical center set: super-topics → topics, with TOTAL-norm
    noise scales (a per-dimension sigma in 384-d would drown the cluster
    signal entirely — noise norm grows with sqrt(d))."""
    supers = _unit(rng.standard_normal((40, dim)).astype(np.float32))
    return _unit(
        supers[rng.integers(0, len(supers), n_centers)]
        + 0.6 * _unit(rng.standard_normal((n_centers, dim)).astype(np.float32))
    )


def clustered_vectors(rng, n, dim, centers):
    """Embedding-like corpus: cos(point, its center) ≈ 0.89."""
    noise = 0.5 * _unit(rng.standard_normal((n, dim)).astype(np.float32))
    return _unit(centers[rng.integers(0, len(centers), n)] + noise).astype(
        np.float32
    )


def run_shard_scale(
    scales=(1_000_000, 2_000_000, 5_000_000, 10_000_000),
    dim: int = 64,
    nprobes=(4, 8, 16, 32, 64),
    batch: int = 20,
    n_queries: int = 60,
    k: int = 10,
    seed: int = 3,
    mesh=None,
    budget_s: Optional[float] = None,
    on_tpu: bool = False,
) -> dict:
    """docqa-meshindex: the 1M→10M sharded-tiered vs exact crossover
    sweep (ROADMAP item 2's "done" evidence).  Per scale: synthetic
    clustered corpus (2000-center mixture — IVF's honest regime, not
    uniform noise), mesh-sharded int8 tiered build, exact-vs-tiered
    latency at batch 20 and batch 1, a measured recall/latency frontier
    over ``nprobes`` (recall vs the exact full-precision scan, Wilson
    CI — quantization loss is INSIDE this number, not hidden), and
    per-chunk/per-shard index bytes.  ``dim`` defaults to 64 (not the
    serving 384) so a 10M sweep fits a CPU box's wall budget; bytes
    scale linearly with dim and the crossover shape does not move.
    Returns the ``DETAILS["shard_scale"]`` dict; also usable standalone
    via ``scripts/shard_scale_bench.py`` (merges into
    bench_details.json)."""
    import gc as _gc

    from docqa_tpu.config import StoreConfig
    from docqa_tpu.index.store import VectorStore
    from docqa_tpu.index.tiered import TieredIndex
    from docqa_tpu.obs.retrieval_observatory import wilson_interval

    if mesh is None:
        from docqa_tpu.runtime.mesh import host_cpu_mesh

        mesh = host_cpu_mesh(8, data=1)
    t_sweep = time.monotonic()
    rng = np.random.default_rng(seed)
    centers = make_centers(rng, 2000, dim)
    shipped_nprobe = StoreConfig().ivf_nprobe
    out: dict = {
        "config": {
            "dim": dim,
            "k": k,
            "batch": batch,
            "n_queries": n_queries,
            "nprobes": list(nprobes),
            "shipped_nprobe": shipped_nprobe,
            "storage": "int8",
            "mesh": {"data": mesh.n_data, "model": mesh.n_model},
            "recall_basis": (
                "vs exact full-precision scan of the live store — "
                "coarse-probe misses AND int8 quantization flips both "
                "count as misses"
            ),
            # honesty label (CPU-degraded rule): latency shape on a
            # 1-core host with 8 virtual devices says nothing about ICI
            "latency_basis": (
                "measured-on-tpu" if on_tpu
                else "cpu-degraded: 8 virtual shards SERIALIZE onto one "
                     "host core, so sharded-tiered ms carry ~n_model x "
                     "the per-chip device work a real mesh runs in "
                     "parallel — recall, bytes, and scan_fraction are "
                     "structural; absolute ms are not v5e evidence "
                     "(ROADMAP open item 5)"
            ),
        },
        "scales": {},
    }
    block = 1 << 18
    for target_n in scales:
        if budget_s is not None and time.monotonic() - t_sweep > budget_s:
            out["scales"][str(target_n)] = "skipped: budget"
            continue
        row: dict = {}
        store = VectorStore(
            StoreConfig(dim=dim, shard_capacity=target_n, dtype="bfloat16"),
            mesh=mesh,
        )
        rngb = np.random.default_rng(seed + target_n)
        t0 = time.perf_counter()
        for start in range(0, target_n, block):
            n = min(block, target_n - start)
            store.add(
                clustered_vectors(rngb, n, dim, centers),
                [{"doc_id": f"s{i}"} for i in range(start, start + n)],
            )
        row["ingest_s"] = round(time.perf_counter() - t0, 1)
        tiered = TieredIndex(
            store,
            min_rows=10_000,
            rebuild_tail_rows=10 * target_n,
            n_clusters=min(4096, int(np.sqrt(target_n))),
        )
        t0 = time.perf_counter()
        tiered.rebuild()
        row["build_s"] = round(time.perf_counter() - t0, 1)
        stats = tiered.index_stats()
        row["index"] = stats
        row["bytes_per_chunk"] = stats["bytes_per_chunk"]
        row["per_shard_mb"] = round(stats["per_shard_bytes"] / 1e6, 1)

        queries = clustered_vectors(rngb, n_queries, dim, centers)
        exact_rows = []
        for start in range(0, n_queries, batch):
            exact_rows.extend(store.search(queries[start : start + batch], k=k))
        exact_ids = [{r.row_id for r in er} for er in exact_rows]
        probes = queries[:batch]

        # crossover: exact vs tiered at the SHIPPED nprobe
        store.search(probes, k=k)  # compile at the timed shape
        t_e20, _ = timed(lambda: store.search(probes, k=k), n=3)
        tiered.search(probes, k=k)
        t_t20, _ = timed(lambda: tiered.search(probes, k=k), n=3)
        one = probes[:1]
        store.search(one, k=k)
        tiered.search(one, k=k)
        t_e1, _ = timed(lambda: store.search(one, k=k), n=5)
        t_t1, _ = timed(lambda: tiered.search(one, k=k), n=5)
        row["exact_batch20_ms"] = round(t_e20 * 1e3, 2)
        row["tiered_batch20_ms"] = round(t_t20 * 1e3, 2)
        row["exact_batch1_ms"] = round(t_e1 * 1e3, 2)
        row["tiered_batch1_ms"] = round(t_t1 * 1e3, 2)
        row["tiered_speedup_batch20"] = round(t_e20 / max(t_t20, 1e-9), 2)

        # recall/latency frontier measured at SERVING semantics: the
        # full tiered.search at each nprobe (widened candidate pool +
        # exact f32 re-rank — the int8 path's shipped policy), recall
        # vs the exact full-precision scan, Wilson CI per the
        # recallscope estimator math.  The tail is empty right after a
        # rebuild, so bulk recall IS tier recall here.
        ivf = tiered._tier[0]
        n_slots = ivf.cap * ivf.n_clusters + max(ivf.n_spilled, 1)
        frontier = []
        for p in nprobes:
            p_eff = min(p, ivf.n_clusters)
            tiered.set_nprobe(p_eff)
            res = []
            for start in range(0, n_queries, batch):
                res.extend(tiered.search(queries[start : start + batch], k=k))
            hits = total = 0
            for want, got_row in zip(exact_ids, res):
                got = {r.row_id for r in got_row}
                hits += len(want & got)
                total += len(want)
            t_p, _ = timed(lambda: tiered.search(probes, k=k), n=3)
            lo, hi = wilson_interval(hits, total)
            frontier.append(
                {
                    "nprobe": p_eff,
                    "recall": round(hits / max(total, 1), 4),
                    "ci_lo": round(lo, 4),
                    "ci_hi": round(hi, 4),
                    "comparisons": total,
                    "tiered_batch20_ms": round(t_p * 1e3, 2),
                    # hardware-independent work model: fraction of the
                    # tier's row slots one query scans (the real-mesh
                    # latency story; CPU ms above serialize all 8
                    # virtual shards onto one core)
                    "scan_fraction": round(
                        (p_eff * ivf.cap + ivf.n_spilled) / n_slots, 4
                    ),
                }
            )
        tiered.set_nprobe(shipped_nprobe)
        row["frontier"] = frontier
        at_shipped = [
            f for f in frontier if f["nprobe"] == min(shipped_nprobe, ivf.n_clusters)
        ]
        if at_shipped:
            row["recall_at_shipped_nprobe"] = {
                "nprobe": at_shipped[0]["nprobe"],
                "recall": at_shipped[0]["recall"],
                "ci": [at_shipped[0]["ci_lo"], at_shipped[0]["ci_hi"]],
            }
        out["scales"][str(target_n)] = row
        log(f"shard_scale {target_n}: {json.dumps(row)}")
        del tiered, store
        _gc.collect()

    # nprobe decision trail (ISSUE 15 satellite): smallest nprobe whose
    # measured recall meets the target at EVERY completed scale — the
    # value StoreConfig.ivf_nprobe ships; recorded here so no future
    # round can quote a tiered speedup without its recall cost
    target = 0.95
    done_rows = [v for v in out["scales"].values() if isinstance(v, dict)]
    qualified = []
    if done_rows:
        for p in nprobes:
            lows = [
                f["ci_lo"]
                for v in done_rows
                for f in v["frontier"]
                if f["nprobe"] == p
            ]
            if lows and min(lows) >= target:
                qualified.append(p)
    out["nprobe_decision"] = {
        "recall_target": target,
        "qualified_nprobes": qualified,
        "chosen": min(qualified) if qualified else None,
        "shipped": shipped_nprobe,
        "rule": (
            "smallest swept nprobe whose Wilson CI LOWER bound on "
            "recall@10 >= target at every completed scale (the CI is the "
            "evidence, not the point estimate); shipped as "
            "StoreConfig.ivf_nprobe / TieredIndex default"
        ),
    }
    out["sweep_wall_s"] = round(time.monotonic() - t_sweep, 1)
    return out


_POOL_DRUGS = (
    "aspirin", "metformin", "lisinopril", "warfarin", "albuterol",
    "atorvastatin", "omeprazole", "amlodipine", "sertraline", "insulin",
    "prednisone", "furosemide", "gabapentin", "levothyroxine", "ramipril",
)
_POOL_CONDITIONS = (
    "type 2 diabetes", "essential hypertension", "atrial fibrillation",
    "chronic heart failure", "asthma exacerbation", "major depression",
    "hypothyroidism", "chronic kidney disease stage 3", "osteoarthritis",
    "gastroesophageal reflux", "stable angina", "migraine without aura",
)
_POOL_FINDINGS = (
    "blood pressure 142 over 88", "heart rate 76 regular",
    "fasting glucose 7.8 mmol per liter", "creatinine 104 umol per liter",
    "oxygen saturation 97 percent on room air", "INR 2.4 in range",
    "HbA1c 7.1 percent improving", "LDL 2.9 mmol per liter",
    "mild pitting edema both ankles", "clear lung fields bilaterally",
)
_POOL_PLANS = (
    "continue current dose and reassess in three months",
    "titrate the dose upward if tolerated at review",
    "order repeat laboratory panel before the next visit",
    "refer to the specialist clinic for further assessment",
    "counselled on diet adherence and daily exercise",
    "monitor for dizziness and report any bleeding promptly",
)


def make_chunk_pool(rng, n_pool: int = 4096):
    """Deterministic pool of realistic clinical chunk texts, 55-110 WORDS
    each (60-120 generator tokens with the whitespace tokenizer) — the
    chunk content the 1M rows cycle through, so the prompt a classic ask
    builds from ``text_content`` and the prompt the fused path packs from
    the token sidecar carry the SAME context (VERDICT r4 item 6)."""
    pool = []
    for i in range(n_pool):
        target = int(rng.integers(55, 110))
        parts = [
            f"Progress note {i}: patient with "
            f"{_POOL_CONDITIONS[rng.integers(0, len(_POOL_CONDITIONS))]} "
            f"reviewed in clinic."
        ]
        n_words = len(parts[0].split())
        while n_words < target:
            sent = (
                f"Current therapy includes "
                f"{_POOL_DRUGS[rng.integers(0, len(_POOL_DRUGS))]} with "
                f"{_POOL_FINDINGS[rng.integers(0, len(_POOL_FINDINGS))]}; "
                f"plan is to "
                f"{_POOL_PLANS[rng.integers(0, len(_POOL_PLANS))]}."
            )
            parts.append(sent)
            n_words += len(sent.split())
        pool.append(" ".join(parts))
    return pool


def dispatch_health(tag: str) -> None:
    """Record the dispatch+sync median under DETAILS["dispatch_ms"].

    On the tunneled client the FIRST device→host fetch of the process
    flips every later synchronization to a flat ~66 ms (async dispatch
    chains stay free — docs/PERF.md §1); local backends read ~0.02 ms
    throughout.  Recording the value at several milestones documents
    which regime each section was measured in."""
    import statistics

    import jax
    import jax.numpy as jnp

    try:
        f = jax.jit(lambda a, b: a @ b)
        x = jnp.ones((128, 128), jnp.bfloat16)
        f(x, x).block_until_ready()
        lat = []
        for _ in range(15):
            t0 = time.perf_counter()
            f(x, x).block_until_ready()
            lat.append((time.perf_counter() - t0) * 1e3)
        DETAILS.setdefault("dispatch_ms", {})[tag] = round(
            statistics.median(lat), 3
        )
    except Exception as e:  # never let the probe cost a section
        DETAILS.setdefault("dispatch_ms", {})[tag] = repr(e)[:80]


def param_bytes(params) -> int:
    return int(sum(np.prod(p.shape) * p.dtype.itemsize for p in params.values()))


def _device_backend_alive(timeout_s: float = 150.0) -> bool:
    """Probe the accelerator from a SUBPROCESS: a dead tunnel hangs
    ``jax.devices()`` indefinitely, and an in-process hang would eat the
    driver's whole bench budget with no JSON line to show for it."""
    import subprocess
    import sys as _sys

    try:
        r = subprocess.run(
            [_sys.executable, "-c", "import jax; jax.devices()"],
            timeout=timeout_s,
            capture_output=True,
        )
        return r.returncode == 0
    except Exception:
        return False


def _device_backend_alive_retrying(
    wait_budget_s: Optional[float] = None,
    probe_timeout_s: float = 120.0,
    backoff_s: float = 45.0,
) -> bool:
    """TIME-budgeted retry/wait around the probe: a transient tunnel
    outage at bench start must not forfeit the whole round to a CPU smoke
    run (it did, twice) — but the budget is bounded because every
    pre-headline minute is driver-window risk (the r04 driver artifact
    was a timeout with the headline already measured but unprinted).

    Probes repeat with backoff until the accelerator answers or
    ``wait_budget_s`` (``DOCQA_BENCH_TPU_WAIT_S``, default 270 s ≈ the
    old 2-probe worst case) is exhausted; only THEN does the caller fall
    back to CPU and stamp ``degraded: true``.  The probe history lands in
    ``DETAILS["backend_probe"]`` so a degraded line is attributable to
    "waited N s across M probes", not a single silent failure."""
    if wait_budget_s is None:
        wait_budget_s = float(os.environ.get("DOCQA_BENCH_TPU_WAIT_S", "270"))
    t0 = time.monotonic()
    attempt = 0
    while True:
        attempt += 1
        # never let one probe overrun what's left of the budget (+grace)
        left = wait_budget_s - (time.monotonic() - t0)
        ok = _device_backend_alive(min(probe_timeout_s, max(left, 30.0)))
        waited = round(time.monotonic() - t0, 1)
        if ok:
            if attempt > 1:
                log(
                    f"accelerator answered on probe attempt {attempt} "
                    f"(+{waited}s)"
                )
            DETAILS["backend_probe"] = {
                "ok": True, "attempts": attempt, "waited_s": waited,
            }
            return True
        left = wait_budget_s - (time.monotonic() - t0)
        if left <= 1.0:
            DETAILS["backend_probe"] = {
                "ok": False, "attempts": attempt, "waited_s": waited,
                "budget_s": wait_budget_s,
            }
            log(
                f"accelerator unreachable after {attempt} probe(s) over "
                f"{waited}s (budget {wait_budget_s:.0f}s)"
            )
            return False
        sleep_s = min(backoff_s, left)
        log(
            f"accelerator probe {attempt} failed (+{waited}s of "
            f"{wait_budget_s:.0f}s budget); retrying in {sleep_s:.0f}s"
        )
        time.sleep(sleep_s)


def _start_stall_watchdog(stall_min: Optional[float] = None) -> None:
    """Abort (exit 3) if NO section lands a measurement for ``stall_min``
    minutes.

    The start-of-run probe retry cannot help once the run is under way: a
    tunnel outage mid-run leaves the axon client sleeping in an internal
    retry loop forever — observed live: a bench 25+ minutes into "one real
    chip" with zero log output, zero IO, and a main thread parked in
    ``clock_nanosleep``.  Progress is defined as DETAILS changing (every
    section writes there, and the corpus loop writes per-block
    breadcrumbs); on stall the watchdog flushes what was measured and
    exits 3 so the outer wrapper (``_run_with_fallback``) can still get
    the driver its one JSON line from a CPU smoke rerun.

    Default 10 min; ``DOCQA_BENCH_STALL_MIN`` raises it for in-session
    runs whose long single calls (multi-million-row IVF builds, beam
    compiles) are legitimate silent stretches."""
    if stall_min is None:
        stall_min = float(os.environ.get("DOCQA_BENCH_STALL_MIN", "10"))
    import threading

    def snap() -> str:
        # dict(DETAILS) snapshots atomically under the GIL; dumping the
        # copy cannot race the main thread's inserts.  The bare fallback
        # must be infallible — an exception here would kill the daemon
        # thread silently and un-watch the rest of the run.
        try:
            return json.dumps(dict(DETAILS), sort_keys=True, default=str)
        except Exception:
            return f"len={len(DETAILS)}"

    state = {"snap": snap(), "t": time.time()}

    def run() -> None:
        while True:
            time.sleep(60)
            try:
                cur = snap()
                if cur != state["snap"]:
                    state["snap"], state["t"] = cur, time.time()
                elif time.time() - state["t"] > stall_min * 60:
                    log(
                        f"WATCHDOG: no measurement progress in "
                        f"{stall_min:.0f} min — device backend likely hung "
                        "mid-run; aborting (exit 3) so the smoke fallback "
                        "can run"
                    )
                    DETAILS["watchdog_abort"] = True
                    flush_details()
                    os._exit(3)
            except Exception as e:  # the watchdog must outlive anything
                log(f"watchdog iteration error (ignored): {e!r}")

    threading.Thread(target=run, daemon=True, name="bench-watchdog").start()


def _run_with_fallback() -> int:
    """Outer wrapper: run the real bench as a child process; if it exits
    without having printed the headline JSON line (watchdog abort, crash,
    or outer-budget timeout), rerun in the forced-CPU smoke configuration
    so the driver ALWAYS receives its one line.  The inner run is selected
    with ``DOCQA_BENCH_INNER=1``."""
    import subprocess
    import threading

    def run_child(extra_env: dict, budget_s: float) -> bool:
        env = dict(os.environ, DOCQA_BENCH_INNER="1", **extra_env)
        p = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__)],
            env=env,
            stdout=subprocess.PIPE,
            text=True,
            bufsize=1,
        )
        got_json = [False]

        def forward() -> None:
            for line in p.stdout:
                sys.stdout.write(line)
                sys.stdout.flush()
                s = line.strip()
                if s.startswith("{"):
                    try:
                        json.loads(s)
                        got_json[0] = True
                    except ValueError:
                        pass

        t = threading.Thread(target=forward, daemon=True)
        t.start()
        deadline = time.time() + budget_s
        while p.poll() is None:
            if time.time() > deadline and not got_json[0]:
                log(
                    f"outer budget ({budget_s:.0f}s) exhausted with no "
                    "headline line — killing the bench child"
                )
                p.kill()
                break
            time.sleep(5)
        p.wait()
        t.join(timeout=30)
        return got_json[0]

    # outer kill-switch: if the real child has not printed the headline by
    # this point, kill it and smoke-rerun — total worst case (1200 s +
    # ~480 s smoke) stays inside the ~30 min driver window r04 ran out of
    budget = float(os.environ.get("DOCQA_BENCH_OUTER_BUDGET_S", "1200"))
    if run_child({}, budget):
        return 0
    log("bench run produced no headline — rerunning as forced-CPU smoke")
    # preserve the aborted real run's partial measurements (the watchdog
    # flushed them) — the smoke child writes the same bench_details.json
    details = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "bench_details.json"
    )
    if os.path.exists(details):
        try:
            os.replace(details, details + ".partial")
            log(f"partial real-run details saved to {details}.partial")
        except OSError as e:
            log(f"could not preserve partial details: {e!r}")
    if run_child(
        {"DOCQA_BENCH_FORCE_CPU": "1", "DOCQA_BENCH_SMALL": "1"}, 600.0
    ):
        return 0
    log("smoke fallback also failed to produce a headline")
    return 1


def _bench_lock(max_wait_s: float = 3600.0) -> None:
    """Cooperative single-runner lock: two benches sharing one chip OOM
    each other into false negatives.  If another live bench holds the
    lock, wait for it (finishing late beats colliding); a stale lock
    (dead pid) is ignored."""
    # flock, not a pid file: acquisition is atomic in the kernel, release is
    # automatic on process death (no stale-pid detection, no TOCTOU between
    # judging a lock stale and unlinking it), and the file itself is never
    # removed so every bench locks the same inode.
    import fcntl

    try:
        fd = os.open("/tmp/docqa_bench.lock", os.O_CREAT | os.O_WRONLY, 0o666)
    except Exception:
        return  # lock is cooperative; never let it kill the bench
    deadline = time.time() + max_wait_s
    while True:
        try:
            fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
            # keep fd open for the process lifetime: closing it would drop
            # the lock (module global, intentionally never closed)
            globals()["_bench_lock_fd"] = fd
            return
        except OSError:
            if time.time() > deadline:
                log("bench lock held past wait budget; proceeding")
                return
            log("bench lock held by another bench; waiting")
            time.sleep(30)


def main() -> None:
    _bench_lock()
    _start_stall_watchdog()
    T0 = time.monotonic()
    # Wall-clock budget for the whole inner run.  The headline path is NOT
    # gated (it must always print); every post-headline section is, so the
    # process exits cleanly inside the driver window no matter what —
    # r04's driver artifact was rc=124/parsed:null with the headline
    # measured but unprinted, which this ordering makes impossible.
    budget_s = float(os.environ.get("DOCQA_BENCH_BUDGET_S", "1300"))

    def remaining() -> float:
        return budget_s - (time.monotonic() - T0)

    force_cpu = os.environ.get("DOCQA_BENCH_FORCE_CPU") == "1"
    if force_cpu or not _device_backend_alive_retrying():
        # degrade honestly: a CPU smoke run labeled as such beats a hang
        log(
            "forced-CPU smoke rerun"
            if force_cpu
            else "accelerator backend unreachable (tunnel down?) — "
            "falling back to the CPU smoke configuration"
        )
        os.environ["DOCQA_BENCH_SMALL"] = "1"
        import jax

        jax.config.update("jax_platforms", "cpu")

    import dataclasses

    import jax

    backend = jax.default_backend()
    on_tpu = backend == "tpu"
    small = (not on_tpu) or os.environ.get("DOCQA_BENCH_SMALL") == "1"

    from docqa_tpu.config import (
        DecoderConfig,
        EncoderConfig,
        GenerateConfig,
        NERConfig,
        StoreConfig,
        SummarizerConfig,
    )
    from docqa_tpu.engines.encoder import EncoderEngine
    from docqa_tpu.engines.generate import GenerateEngine
    from docqa_tpu.index.store import VectorStore
    from docqa_tpu.runtime.mesh import make_mesh
    from docqa_tpu.text.tokenizer import default_tokenizer

    n_chunks = 20_000 if small else 1_000_000
    max_new = 16 if small else 64
    n_queries = 5 if small else 20
    # 7B e2e sample count: 5-sample p50s swung 445-683 ms run to run on
    # the tunnel; 15 asks cost ~10 s per engine and cut that spread
    n_e2e = 5 if small else 15
    dec_cfg = (
        DecoderConfig()  # smoke size
        if small
        else DecoderConfig(  # ~1.1B-param class serving model
            vocab_size=32000,
            hidden_dim=2048,
            num_layers=16,
            num_heads=16,
            num_kv_heads=8,
            head_dim=128,
            mlp_dim=5632,
            max_seq_len=4096,
        )
    )
    cfg7 = DecoderConfig.mistral_7b()

    mesh = make_mesh() if jax.device_count() > 1 else None
    DETAILS["backend"] = backend
    DETAILS["n_chunks"] = n_chunks
    DETAILS["budget_s"] = budget_s

    # ---- bench-wide telemetry: one sampler over the default registry for
    # the whole run; the rollup snapshot lands in DETAILS["telemetry_
    # snapshot"] at exit, so every bench artifact carries its own
    # time-series record (when a number looks wrong, the series says
    # whether it degraded mid-run or ran degraded throughout)
    from docqa_tpu import obs as _obs_bench
    from docqa_tpu.runtime.metrics import DEFAULT_REGISTRY as _REG

    _bench_tstore = _obs_bench.TelemetryStore(interval_s=10.0, points=360)
    _bench_sampler = _obs_bench.TelemetrySampler(
        _bench_tstore,
        registry=_REG,
        recorder=_obs_bench.DEFAULT_RECORDER,
        sample_every_s=2.0,
        hbm_refresh_s=0,
    ).start()

    # ---- corpus: 1M clustered chunks with REALISTIC texts, HBM-resident ----
    rng = np.random.default_rng(0)
    dim = 384
    centers = make_centers(rng, 2000, dim)
    W = 128  # token sidecar width (+512 MB at 1M rows)
    # chunk texts + sidecar tokens cycle through a realistic pool so the
    # fused and classic ask paths carry EQUAL context (VERDICT r4 item 6)
    pool_texts = make_chunk_pool(
        np.random.default_rng(7), 1024 if small else 4096
    )
    gen_vocab = dec_cfg.vocab_size if small else cfg7.vocab_size
    gen_tok = default_tokenizer(gen_vocab)
    n_pool = len(pool_texts)
    pool_tok = np.zeros((n_pool, W), np.int32)
    pool_len = np.zeros((n_pool,), np.int32)
    for i, t in enumerate(pool_texts):
        ids = gen_tok.encode(t, add_specials=False)[:W]
        pool_tok[i, : len(ids)] = ids
        pool_len[i] = len(ids)
    DETAILS["chunk_pool"] = {
        "n": n_pool,
        "token_len_mean": round(float(pool_len.mean()), 1),
        "token_len_min": int(pool_len.min()),
        "token_len_max": int(pool_len.max()),
    }

    encoder = EncoderEngine(EncoderConfig(), mesh=mesh)
    store = VectorStore(
        StoreConfig(shard_capacity=max(n_chunks, 16384), token_width=W),
        mesh=mesh,
    )
    t0 = time.perf_counter()
    block = 131_072
    for start in range(0, n_chunks, block):
        n = min(block, n_chunks - start)
        vecs = clustered_vectors(rng, n, dim, centers)
        idx = np.arange(start, start + n) % n_pool
        store.add(
            vecs,
            [
                {
                    "doc_id": f"d{i}",
                    "source": f"chunk {i}",
                    "text_content": pool_texts[i % n_pool],
                    "type": "kb",
                }
                for i in range(start, start + n)
            ],
            token_rows=pool_tok[idx],
            token_lens=pool_len[idx],
        )
        # watchdog breadcrumb: each ~200 MB block transfer is progress
        DETAILS["ingest_rows"] = start + n
    log(f"corpus: {n_chunks} chunks ingested in {time.perf_counter()-t0:.1f}s")
    dispatch_health("after_corpus")

    # ---- config 1: retrieval (encode + exact top-k at 1M) -------------------
    q_texts = [
        f"What therapy best controls condition {i} and at what dose?"
        for i in range(n_queries + 2)
    ]
    from docqa_tpu.engines.retrieve import FusedRetriever

    retriever = FusedRetriever(encoder, store)
    emb0 = encoder.encode_texts([q_texts[0]])  # compile
    store.search(emb0, k=3)
    store.search(emb0, k=10)  # the timed shape (jit key includes k)
    retriever.search_texts([q_texts[0]], k=3)  # compile fused (headline shape)
    retriever.search_texts([q_texts[0]], k=10)
    t_enc, _ = timed(lambda: encoder.encode_texts([q_texts[1]]), n=5)
    t_search, _ = timed(lambda: store.search(emb0, k=10), n=5)
    t_fused, _ = timed(lambda: retriever.search_texts([q_texts[1]], k=10), n=5)
    DETAILS["retrieval"] = {
        "encode_ms": round(t_enc * 1e3, 2),
        "exact_top10_ms": round(t_search * 1e3, 2),
        "fused_query_top10_ms": round(t_fused * 1e3, 2),
    }
    log(
        f"config1 retrieval: encode {t_enc*1e3:.1f}ms, "
        f"exact top-10 @ {n_chunks}: {t_search*1e3:.1f}ms, "
        f"fused text->top-10: {t_fused*1e3:.1f}ms"
    )
    flush_details()

    # ---- shared measurement helpers -----------------------------------------
    def make_ask(engine, retr=None):
        """Classic ask loop (search -> context join -> decode).  ``retr``
        swaps the retrieval path (sec_retrieval_quality's tiered A/B);
        default is the fused-exact retriever."""
        r = retr if retr is not None else retriever

        def ask(q: str) -> None:
            hits = r.search_texts([q], k=3)[0]
            ctx = "\n\n".join(
                h.metadata.get("text_content") or h.metadata["source"]
                for h in hits
            )
            prompt = f"Context:\n{ctx}\n\nQuestion: {q}\nAnswer:"
            engine.generate_texts([prompt], max_new_tokens=max_new)

        return ask

    def measure_e2e(engine, queries, tag):
        ask = make_ask(engine)
        for q in q_texts[:2]:  # compile prefill/decode at the served shapes
            ask(q)
        lat = []
        for q in queries:
            t0 = time.perf_counter()
            ask(q)
            lat.append((time.perf_counter() - t0) * 1000.0)
        p50 = float(np.percentile(lat, 50))
        p95 = float(np.percentile(lat, 95))
        log(f"{tag} e2e: p50 {p50:.1f}ms p95 {p95:.1f}ms ({max_new} new tokens)")
        return p50, p95

    def measure_decode(engine, key, tag):
        pb = param_bytes(engine.params)
        n_tok = 64 if not small else 8
        engine.generate_ids([[5, 9, 11]], max_new_tokens=n_tok)  # compile
        t_dec, _ = timed(
            lambda: engine.generate_ids([[5, 9, 11]], max_new_tokens=n_tok),
            n=3,
        )
        tok_s = n_tok / t_dec
        # real per-root HBM bytes via the decode program's AOT
        # memory_analysis (the same measurement compile_audit gates) —
        # argument bytes are the true resident working set (weights + KV
        # cache + token inputs), not the host-side param_bytes estimate
        ma = engine.decode_memory_analysis(
            prompt_len=3, max_new_tokens=n_tok
        )
        # utilization = weight-bytes-read bandwidth demand vs v5e peak.
        # Off-TPU this is the PROJECTED demand of the same program on a
        # v5e, labeled as such — never null (BENCH_r05 reported null
        # because nobody measured per-root HBM; VERDICT item)
        hbm_util = tok_s * pb / (V5E_HBM_GBPS * 1e9)
        DETAILS[key] = {
            "tokens_per_s": round(tok_s, 1),
            "param_bytes_gb": round(pb / 1e9, 2),
            "hbm_resident_bytes": (
                int(ma["argument_bytes"]) if ma else pb
            ),
            "hbm_peak_bytes": int(ma["peak_bytes"]) if ma else None,
            "hbm_utilization": round(hbm_util, 3),
            "hbm_utilization_basis": (
                "measured-on-v5e" if on_tpu else "projected-v5e (CPU run)"
            ),
        }
        log(
            f"{tag} decode ({pb/1e9:.1f}GB params): {tok_s:.0f} tok/s, "
            f"HBM util {hbm_util:.0%}"
            + ("" if on_tpu else " (projected)")
        )

    def measure_fused(engine, tag, extra=None):
        # single-sync ask: retrieval -> device-side prompt pack -> decode
        # chained with no intermediate fetch (engines/rag_fused.py); the
        # classic path above pays one extra sync for the chunk texts.
        # Context is EQUAL on both paths now: the sidecar holds the same
        # pool tokens the classic path reads as text_content.
        from docqa_tpu.engines.rag_fused import FusedRAG
        from docqa_tpu.service.qa import QA_TEMPLATE

        rag = FusedRAG(encoder, store, engine, QA_TEMPLATE, k=3)
        rag.ask(q_texts[0], max_new_tokens=max_new)  # compile
        lats = []
        for q in q_texts[2 : 2 + n_e2e]:
            t0 = time.perf_counter()
            rag.ask(q, max_new_tokens=max_new)
            lats.append((time.perf_counter() - t0) * 1e3)
        p50f = float(np.percentile(lats, 50))
        p95f = float(np.percentile(lats, 95))
        DETAILS[tag] = {
            "p50_ms": round(p50f, 2),
            "p95_ms": round(p95f, 2),
            "new_tokens": max_new,
            **(extra or {}),
        }
        log(f"{tag}: p50 {p50f:.1f}ms p95 {p95f:.1f}ms")
        return p50f, p95f

    # ---- HEADLINE: e2e QA latency, measured FIRST, printed IMMEDIATELY ------
    # Serving default is int8 weight-only (w8a16, models/quant.py): decode
    # is HBM-bandwidth bound, so halving the weight bytes read per step is
    # the biggest latency lever.  The 7B class (BASELINE config 3's model
    # class) is the headline; speculation k=8 was the measured winner of
    # both the r04 sweep (573 vs 617 ms at k=4) and r05 (754 vs 805) —
    # the k=4 comparator re-measures post-headline.
    #
    # The headline PATH is the fused single-sync ask (engines/rag_fused.py)
    # — it is what QAService actually serves an interactive /ask with when
    # the batcher is idle, and with the equal-context corpus it measured
    # faster than the classic two-sync path at both model classes (r05:
    # 579 vs 754 ms at 7B, 285 vs 387 at 1.1B; docs/PERF.md §1).  The
    # classic path is measured post-headline as the A/B comparator; any
    # fused failure falls back to classic BEFORE the line prints.
    S: dict = {"gen8": None, "params8": None, "gen1": None}
    p50 = p95 = None
    head_engine = None
    if not small:
        try:
            from docqa_tpu.models.quant import init_quantized_decoder_params

            HEAD_SPEC_K = 8
            # HOST init: the device-side jax.random init sequence leaves
            # the tunneled client in its degraded mode (docs/PERF.md §1,
            # ~70 ms on EVERY later dispatch) and everything measured in
            # this process runs after this point.
            S["params8"] = init_quantized_decoder_params(
                jax.random.PRNGKey(0), cfg7, host_init=True, host_seed=0
            )
            S["gen8"] = GenerateEngine(
                cfg7,
                GenerateConfig(
                    max_new_tokens=64,
                    prefill_buckets=(512, 1024),
                    speculative_k=HEAD_SPEC_K,
                ),
                params=S["params8"],
            )
            dispatch_health("before_headline")
            head_engine = S["gen8"]
        except Exception as e:
            log(f"7B init failed, falling back to 1.1B-int8: {e!r}")
            DETAILS["qa_e2e_7b_int8"] = {"error": repr(e)[:500]}
            S["gen8"] = S["params8"] = None
            gc.collect()
    if head_engine is None:
        # small mode, or the 7B init failed: the 1.1B-int8 serving class
        S["gen1"] = GenerateEngine(
            dataclasses.replace(dec_cfg, quantize_weights=True), mesh=mesh
        )
        head_engine = S["gen1"]
    head_name = "7b_int8" if head_engine is S["gen8"] else "1b_int8"
    head_decoder = (
        "mistral-7b-class-int8"
        if head_name == "7b_int8"
        else f"{dec_cfg.hidden_dim}x{dec_cfg.num_layers}-int8"
    )
    head_provenance = {
        "decoder": head_decoder,
        "speculative_k": head_engine.gen.speculative_k,
        "context": "3 x 60-120-token chunks (realistic pool)",
    }
    try:
        p50, p95 = measure_fused(
            head_engine, f"qa_e2e_{head_name}_fused", extra=head_provenance
        )
        DETAILS["headline_config"] = f"qa_e2e_{head_name}_fused"
        log(f"HEADLINE fused {head_name}: p50 {p50:.1f}ms")
    except Exception as e:
        log(f"fused headline failed, classic path takes the line: {e!r}")
        DETAILS[f"qa_e2e_{head_name}_fused"] = {"error": repr(e)[:300]}
        p50, p95 = measure_e2e(
            head_engine,
            q_texts[2 : 2 + n_e2e],
            f"HEADLINE classic {head_name}",
        )
        key = "qa_e2e_7b_int8" if head_name == "7b_int8" else "qa_e2e"
        DETAILS[key] = {
            "p50_ms": round(p50, 2),
            "p95_ms": round(p95, 2),
            "new_tokens": max_new,
            **head_provenance,
            "attempts": [
                {
                    "speculative_k": head_engine.gen.speculative_k,
                    "p50_ms": round(p50, 2),
                    "p95_ms": round(p95, 2),
                }
            ],
        }
        DETAILS["headline_config"] = key

    # ---- EMIT THE ONE LINE (before everything else) -------------------------
    # A CPU fallback run must be UNMISTAKABLE in the one line the driver
    # parses: distinct metric name AND an explicit degraded flag.
    degraded = not on_tpu
    DETAILS["degraded"] = degraded
    if degraded:
        # degraded is stamped ONLY after the TPU retry budget was spent
        # (or an explicit forced-CPU rerun) — the reason says which
        probe = DETAILS.get("backend_probe")
        DETAILS["degraded_reason"] = (
            "forced_cpu_rerun"
            if force_cpu
            else "backend_unreachable_after_retry_budget"
            if probe and not probe.get("ok")
            else "cpu_backend"
        )
    DETAILS["headline_printed_at_s"] = round(time.monotonic() - T0, 1)
    flush_details()
    summary = {
        "metric": "qa_e2e_p50_ms" + ("_cpu_smoke" if degraded else ""),
        "value": round(p50, 2),
        "unit": "ms",
        "vs_baseline": round(1000.0 / p50, 3),
    }
    if degraded:
        summary["degraded"] = True
    print(json.dumps(summary), flush=True)
    log(f"headline printed at +{DETAILS['headline_printed_at_s']}s")
    # the engines stay reachable through S only: a lingering head_engine
    # reference would pin the 7B tree (or the 1.1B fallback engine) past
    # the explicit frees the HBM-hungry sections below rely on
    head_engine = None

    # ---- post-headline sections, each budget-gated --------------------------
    def run_section(name: str, fn, need_s: float = 90.0) -> bool:
        if remaining() < need_s:
            DETAILS.setdefault("skipped", {})[name] = (
                f"budget: {remaining():.0f}s left, need ~{need_s:.0f}s"
            )
            log(f"SKIP {name}: {DETAILS['skipped'][name]}")
            flush_details()
            return False
        log(f"section {name} (budget left {remaining():.0f}s)")
        try:
            fn()
        except Exception as e:
            log(f"section {name} failed: {e!r}")
            DETAILS.setdefault("section_errors", {})[name] = repr(e)[:300]
        flush_details()
        return True

    # ---- load harnesses ------------------------------------------------------
    def trace_stats(traces):
        """Fold completed request traces (docqa_tpu/obs) into the
        per-stage attribution record the load sections report: stage
        table, device/host split, and span coverage of request wall time
        (the ≥95% acceptance figure — an unattributed gap means a stage
        nobody instrumented ate latency)."""
        from docqa_tpu import obs

        done = [t for t in traces if t is not None and t.finished]
        if not done:
            return None
        rows = obs.attribution(done)
        covs = [obs.coverage(t) for t in done]
        return {
            "n_traces": len(done),
            "trace_coverage_mean": round(float(np.mean(covs)), 4),
            "trace_coverage_min": round(float(min(covs)), 4),
            "device_host_split": obs.device_host_split(done),
            "stage_attribution": rows,
        }

    def dispatch_window(stage_prefixes=("serve_",)):
        """Snapshot the dispatch spine + observatory (docqa-observatory);
        returns a closure computing the measured window's per-stage
        device time, queue wait, and MFU — sourced from spine stats at
        the one-fetch-per-dispatch boundary, NOT host wall-clock.  On a
        CPU smoke run MFU is a ratio against the projected v5e peak and
        is labeled so (``peak_flops_source``).  Only stages matching
        ``stage_prefixes`` enter the TOTALS (device_time_share / mfu):
        the spine is process-wide, and an unrelated concurrent item — a
        telemetry HBM-probe compile, background store traffic — must not
        contaminate the section's headline numbers (other stages still
        appear in the map, marked ``in_totals: false``)."""
        from docqa_tpu import obs as _obs
        from docqa_tpu.engines.spine import get_spine

        spine = get_spine()
        s0 = spine.stats()
        o0 = _obs.DEFAULT_OBSERVATORY.stats()

        def finish(wall_s):
            s1 = spine.stats()
            o1 = _obs.DEFAULT_OBSERVATORY.stats()
            peak = o1["peak"]
            stages = {}
            tot_dev = 0.0
            tot_flops = 0.0
            for name, row in s1["stages"].items():
                b = s0["stages"].get(name, {})
                d_cnt = row["count"] - b.get("count", 0)
                d_dev = row["device_s"] - b.get("device_s", 0.0)
                d_qw = row["queue_wait_s"] - b.get("queue_wait_s", 0.0)
                if d_cnt <= 0 and d_dev <= 0:
                    continue
                in_totals = name.startswith(tuple(stage_prefixes))
                entry = {
                    "count": d_cnt,
                    "device_ms": round(d_dev * 1e3, 2),
                    "queue_wait_ms": round(d_qw * 1e3, 2),
                    "mfu": None,
                    "in_totals": in_totals,
                }
                oa = o1["stages"].get(name)
                if oa is not None:
                    ob = o0["stages"].get(name) or {}
                    d_fl = oa["flops"] - ob.get("flops", 0.0)
                    od_dev = oa["device_s"] - ob.get("device_s", 0.0)
                    if d_fl > 0 and od_dev > 0:
                        mfu = d_fl / od_dev / peak["peak_flops"]
                        if mfu > 1.0:
                            # impossible ratio = this stage's fetch
                            # boundary under-measures device time on a
                            # synchronous-dispatch backend (CPU smoke);
                            # never claim it as utilization
                            entry["mfu_raw_invalid"] = round(mfu, 6)
                        else:
                            entry["mfu"] = round(mfu, 6)
                            if in_totals:
                                tot_flops += d_fl
                if in_totals:
                    tot_dev += d_dev
                stages[name] = entry
            return {
                "stages": stages,
                "device_time_s": round(tot_dev, 4),
                "device_time_share": (
                    round(tot_dev / wall_s, 4) if wall_s else None
                ),
                "mfu": (
                    round(tot_flops / tot_dev / peak["peak_flops"], 6)
                    if tot_dev > 0 and tot_flops > 0
                    else None
                ),
                "peak_flops": peak["peak_flops"],
                "peak_flops_source": peak["peak_flops_source"],
            }

        return finish

    def run_load(engine, n_slots, chunk, n_req, cache_len,
                 kv_pool_tokens=None, session_mix=None, prefix_cache=None):
        """Closed-loop load: n_req concurrent requests, max_new tokens
        each, through a ContinuousBatcher.  Returns (qps, wall_s, lat_ms,
        traces, telemetry) where lat_ms are submit->done completion
        latencies, traces are the per-request obs timelines (queue-wait /
        prefill / decode-chunk / result-wait attribution), and telemetry
        is the live sampler's view of the run: queue depth / block-pool
        occupancy / per-token KV bytes series plus the sampler's own CPU
        share, asserted against the 2% observability budget (soft —
        recorded and logged, bench keeps measuring).  ``kv_pool_tokens``
        overcommits the paged KV pool below worst case (the kv_paging
        sweep's fixed-HBM knob).  ``session_mix`` replaces the default
        unique-prompt burst with an explicit [(prompt_ids, prefix_key)]
        list — the repeat-heavy prefix_reuse section's knob — and
        ``prefix_cache`` force-enables/disables the KV prefix cache for
        the A/B; warm-prefix hit economics always ride out in
        ``telemetry["prefix"]`` (zeros on a cold unique mix — honest
        first-class columns either way)."""
        import threading as _threading

        from docqa_tpu import obs
        from docqa_tpu.engines.serve import ContinuousBatcher
        from docqa_tpu.runtime.metrics import DEFAULT_REGISTRY as _REG

        b = ContinuousBatcher(
            engine, n_slots=n_slots, chunk=chunk, cache_len=cache_len,
            kv_pool_tokens=kv_pool_tokens, prefix_cache=prefix_cache,
        )
        # the sampler runs DURING the measured window deliberately: the
        # serving config ships with it on, so the measured QPS includes
        # its cost (the A/B that isolates that cost is sec_telemetry_
        # overhead; here we only bound its CPU share)
        tstore = obs.TelemetryStore(interval_s=1.0, points=600)
        sampler = obs.TelemetrySampler(
            tstore, batcher=b, sample_every_s=0.25, hbm_refresh_s=0
        ).start()
        try:
            # BOTH admission shape families (4-lane trickle + full
            # n_slots), ahead of the measurement — the drain tail of a
            # closed-loop burst admits 1-2 requests per round and used to
            # pay the trickle compile inside the timed window.  Only the
            # smallest bucket: these 5-token prompts never leave it, and
            # sweep_load builds a FRESH batcher per grid point (a full
            # ladder would be dozens of dead-shape compiles at 7B)
            b.warmup(buckets=b.gen.prefill_buckets[:1])
            # register the programs' cost_analysis() FLOPs so the spine
            # window below yields per-stage MFU, not just device time
            b.annotate_costs()
            if session_mix is not None:
                n_req = len(session_mix)
                prompt_ids = [p for p, _k in session_mix]
                prefix_keys = [k for _p, k in session_mix]
            else:
                prompt_ids = [
                    [7 + i % 13, 5, 9, 11, 3 + i % 7] for i in range(n_req)
                ]
                prefix_keys = [None] * n_req
            for h in [
                b.submit_ids(p, max_new_tokens=4) for p in prompt_ids[:n_slots]
            ]:
                h.result()
            b.submit_ids(prompt_ids[0], max_new_tokens=max_new).result()
            lat_ms = [0.0] * n_req
            traces = [None] * n_req
            waiters = []
            warm_tick_s = sampler.tick_seconds  # exclude warmup-era ticks
            dispatch_fin = dispatch_window()
            hits0 = _REG.counter("serve_prefix_hits").value
            avoided0 = _REG.counter("serve_prefix_tokens_avoided").value
            t0 = time.perf_counter()

            def wait_one(idx, handle, ctx):
                handle.result()
                lat_ms[idx] = (time.perf_counter() - t0) * 1e3
                obs.finish(ctx)
                traces[idx] = ctx.trace if ctx else None

            for i, p in enumerate(prompt_ids):
                ctx = obs.new_trace("rag_load")
                h = obs.call_in(
                    ctx, b.submit_ids, p, max_new_tokens=max_new,
                    prefix_key=prefix_keys[i],
                )
                w = _threading.Thread(target=wait_one, args=(i, h, ctx))
                w.start()
                waiters.append(w)
            for w in waiters:
                w.join()
            wall = time.perf_counter() - t0
            hits = _REG.counter("serve_prefix_hits").value - hits0
            avoided = (
                _REG.counter("serve_prefix_tokens_avoided").value - avoided0
            )
            dispatch = dispatch_fin(wall)
            kv_static = b.kv_block_occupancy()  # pool geometry (post-run)
        finally:
            sampler.stop()
            b.stop()
            del b
            gc.collect()
        # CPU share over the MEASURED window only: ticks spent during
        # warmup (compiles stretch it) would inflate the numerator
        # against a denominator that starts at t0
        share_pct = (
            (sampler.tick_seconds - warm_tick_s) / wall * 100.0
            if wall > 0
            else 0.0
        )

        def _series_max(name):
            s = tstore.series(name)
            vals = [
                p.get("value") for p in (s or {}).get("points", [])
                if isinstance(p.get("value"), (int, float))
            ]
            return max(vals) if vals else 0.0

        peak_blocks = _series_max("serve_kv_blocks_used")
        kv = {
            # per-token KV HBM at block granularity — the paged
            # accounting ROADMAP item 1 demands instead of per-bucket
            "bytes_per_token": kv_static["bytes_per_token"],
            "block_size": kv_static["block_size"],
            "blocks_total": kv_static["blocks_total"],
            "pool_bytes": kv_static["pool_bytes"],
            "peak_blocks_used": int(peak_blocks),
            "peak_kv_bytes": int(
                peak_blocks * kv_static["block_size"]
                * kv_static["bytes_per_token"]
            ),
            "peak_utilization": round(
                peak_blocks / max(kv_static["blocks_total"], 1), 3
            ),
        }
        telemetry = {
            "kv": kv,
            # warm-prefix economics over the measured window
            # (docqa-prefix): hit rate across this run's admissions and
            # the prefill tokens the cache served from shared blocks
            "prefix": {
                "warm_prefix_hit_rate": (
                    round(hits / n_req, 4) if n_req else 0.0
                ),
                "prefill_tokens_avoided": int(avoided),
                "hits": int(hits),
            },
            # spine-sourced device attribution: per-stage device time /
            # queue wait / MFU over the measured window (docqa-observatory)
            "dispatch": dispatch,
            "sampler_ticks": sampler.ticks,
            "sampler_cpu_share_pct": round(share_pct, 3),
            "sampler_budget_pct": 2.0,
            "within_budget": share_pct <= 2.0,
            "series": {
                name: tstore.series(name)
                for name in tstore.names()
                if name.startswith(("serve_", "pool_"))
            },
        }
        if not telemetry["within_budget"]:
            log(
                f"TELEMETRY BUDGET EXCEEDED: sampler CPU share "
                f"{share_pct:.2f}% > 2% of the measured window"
            )
        return n_req / wall, wall, lat_ms, traces, telemetry

    def sweep_load(engine, n_req, cache_len, grid):
        """Closed-loop knob grid over (n_slots, chunk); the served config
        should be the measured winner, not a guess.  Stops early once the
        target is comfortably beaten (QPS >= 20)."""
        attempts = []
        qps, wall, lat, traces, telem = run_load(
            engine, *grid[0], n_req, cache_len
        )
        attempts.append(
            {"n_slots": grid[0][0], "chunk": grid[0][1], "qps": round(qps, 2)}
        )
        if not small:
            for ns, ch in grid[1:]:
                if qps >= 20:
                    attempts.append({"skipped_past": f"({ns},{ch})"})
                    break
                try:
                    q2, w2, l2, tr2, tl2 = run_load(
                        engine, ns, ch, n_req, cache_len
                    )
                except Exception as e:
                    log(f"load sweep ({ns},{ch}) failed: {e!r}")
                    continue
                attempts.append(
                    {"n_slots": ns, "chunk": ch, "qps": round(q2, 2)}
                )
                if q2 > qps:
                    qps, wall, lat, traces, telem = q2, w2, l2, tr2, tl2
        best = max((a for a in attempts if "qps" in a), key=lambda a: a["qps"])
        out = {
            "arrival": "closed-loop burst",
            "requests": n_req,
            "wall_s": round(wall, 2),
            "sustained_qps": round(qps, 2),
            "qps_target": 16,
            "request_p50_ms": round(float(np.percentile(lat, 50)), 1),
            "request_p95_ms": round(float(np.percentile(lat, 95)), 1),
            "best_knobs": {"n_slots": best["n_slots"], "chunk": best["chunk"]},
            "attempts": attempts,
            # device-time attribution from spine stats (NOT host wall):
            # share of the measured wall the device actually worked, and
            # FLOPs-based MFU per the observatory's cost models
            "mfu": (telem.get("dispatch") or {}).get("mfu"),
            "device_time_share": (
                (telem.get("dispatch") or {}).get("device_time_share")
            ),
            "dispatch": telem.get("dispatch"),
            # first-class paged-KV accounting for the winner run:
            # per-token bytes, block-pool peak occupancy (the ROADMAP
            # item 1 before/after evidence)
            "kv": telem.get("kv"),
            # first-class warm-prefix columns (docqa-prefix): zero on
            # this unique-prompt mix by construction — the repeat-heavy
            # session economics live in DETAILS["prefix_reuse"]
            "warm_prefix_hit_rate": (
                (telem.get("prefix") or {}).get("warm_prefix_hit_rate")
            ),
            "prefill_tokens_avoided": (
                (telem.get("prefix") or {}).get("prefill_tokens_avoided")
            ),
            # recall honesty column (docqa-recallscope): stamped by
            # sec_retrieval_quality with the online shadow estimate, so
            # no round can quote a tiered speedup without its recall
            # cost beside it; null means the estimator did not run
            "retrieval_recall": None,
            # the winner run's live telemetry: queue/block-pool/KV
            # series + the sampler's measured CPU share vs its 2% budget
            "telemetry": telem,
        }
        stats = trace_stats(traces)
        if stats is not None:
            out.update(stats)
            from docqa_tpu import obs

            log(
                "rag_load per-stage attribution (winner config):\n"
                + obs.format_table(stats["stage_attribution"])
            )
        return out

    def run_open_loop(engine, n_slots, chunk, cache_len, qps_target, n_req):
        """OPEN-loop load (VERDICT r4 item 3): requests arrive on a fixed
        schedule at exactly ``qps_target``, with RAG-realistic prompt
        lengths (template + 3 pool chunks + question, ~300 tokens).
        Latency is measured from each request's SCHEDULED arrival, so
        queueing delay counts — this is the latency-under-target-load
        number BASELINE's metric names.  Queue depth is sampled at 20 Hz."""
        import threading as _threading

        from docqa_tpu import obs
        from docqa_tpu.engines.serve import ContinuousBatcher

        rngp = np.random.default_rng(3)
        prompts = []
        for i in range(n_req + n_slots):
            parts = [5, 9, 11]
            for j in rngp.integers(0, n_pool, 3):
                row = pool_tok[int(j)][: int(pool_len[int(j)])]
                parts.extend(int(t) for t in row)
            parts.extend((7 + i % 13, 3 + i % 7))
            prompts.append(parts)
        b = ContinuousBatcher(
            engine, n_slots=n_slots, chunk=chunk, cache_len=cache_len
        )
        try:
            # compile BOTH admission shape families for every bucket
            # BEFORE t0: an open loop at QPS 16 admits 1-2 requests per
            # round, and the 4-lane trickle prefill shape used to compile
            # inside the first measured request (the r05 open-loop wall)
            b.warmup()
            for h in [
                b.submit_ids(p, max_new_tokens=4) for p in prompts[:n_slots]
            ]:
                h.result()
            b.submit_ids(prompts[0], max_new_tokens=max_new).result()
            # per-request outcome: a failed/shed request must not leave a
            # placeholder 0.0 in the latency sample (it used to pull p50
            # DOWN exactly when the batcher was failing)
            lat_ms = [0.0] * n_req
            ok = [False] * n_req
            req_traces = [None] * n_req
            qdepth: list = []
            done_evt = _threading.Event()

            def sampler():
                while not done_evt.is_set():
                    qdepth.append(b.n_queued)
                    time.sleep(0.05)

            smp = _threading.Thread(target=sampler, daemon=True)
            smp.start()
            waiters = []
            t0 = time.perf_counter()

            def wait_one(idx, handle, sched, ctx):
                try:
                    handle.result()
                except Exception:
                    obs.finish(ctx, status="error")
                    req_traces[idx] = ctx.trace if ctx else None
                    return  # counted in errors; latency sample excluded
                ok[idx] = True
                lat_ms[idx] = (time.perf_counter() - sched) * 1e3
                obs.finish(ctx)
                req_traces[idx] = ctx.trace if ctx else None

            for i in range(n_req):
                sched = t0 + i / qps_target
                now = time.perf_counter()
                if sched > now:
                    time.sleep(sched - now)
                ctx = obs.new_trace("rag_open_loop")
                try:
                    h = obs.call_in(
                        ctx, b.submit_ids, prompts[n_slots + i],
                        max_new_tokens=max_new,
                    )
                except Exception:
                    obs.finish(ctx, status="error")
                    continue  # shed at admission: an error, not a latency
                w = _threading.Thread(target=wait_one, args=(i, h, sched, ctx))
                w.start()
                waiters.append(w)
            for w in waiters:
                w.join()
            wall = time.perf_counter() - t0
            done_evt.set()
            smp.join(timeout=2)
        finally:
            b.stop()
            del b
            gc.collect()
        good = [l for l, k in zip(lat_ms, ok) if k]
        errors = n_req - len(good)
        stats = trace_stats(req_traces)
        if stats is not None:
            from docqa_tpu import obs as _obs

            log(
                f"open@{qps_target} per-stage attribution:\n"
                + _obs.format_table(stats["stage_attribution"])
            )
        return {
            "arrival": f"open@{qps_target}",
            "requests": n_req,
            "requests_ok": len(good),
            "errors": errors,
            **(stats or {}),
            "wall_s": round(wall, 2),
            "achieved_qps": round(len(good) / wall, 2),
            "request_p50_ms": (
                round(float(np.percentile(good, 50)), 1) if good else None
            ),
            "request_p95_ms": (
                round(float(np.percentile(good, 95)), 1) if good else None
            ),
            "queue_depth_max": int(max(qdepth)) if qdepth else 0,
            "queue_depth_mean": (
                round(float(np.mean(qdepth)), 1) if qdepth else 0.0
            ),
            "prompt_tokens": "~300 (template + 3 pool chunks)",
            "n_slots": n_slots,
            "chunk": chunk,
        }

    late_sections = []

    # ---- 7B sections (params live from the headline) ------------------------
    if S["gen8"] is not None:

        def sec_decode_7b():
            # decode tok/s; the engine's smallest prefill bucket is 512
            # (the realistic-prompt shape), so the number includes one
            # 512-token prefill — noted, and conservative by ~5%
            measure_decode(S["gen8"], "decode_7b_int8", "config3c 7B int8")
            DETAILS["decode_7b_int8"]["includes_prefill"] = 512

        def sec_classic_7b():
            # the classic two-sync path: the fused headline's A/B
            # comparator (equal context — same pool chunks both ways).
            # Provenance comes from the ENGINE, not literals — the
            # headline's head_provenance dict is reused so a future
            # HEAD_SPEC_K change cannot desynchronize the record.
            if "p50_ms" in DETAILS.get("qa_e2e_7b_int8", {}):
                return  # headline fell back to classic; already measured
            k_eng = S["gen8"].gen.speculative_k
            p50c, p95c = measure_e2e(
                S["gen8"],
                q_texts[2 : 2 + n_e2e],
                f"7B-int8 classic spec_k={k_eng}",
            )
            DETAILS["qa_e2e_7b_int8"] = {
                "p50_ms": round(p50c, 2),
                "p95_ms": round(p95c, 2),
                "new_tokens": max_new,
                **head_provenance,
                "attempts": [
                    {
                        "speculative_k": k_eng,
                        "p50_ms": round(p50c, 2),
                        "p95_ms": round(p95c, 2),
                    }
                ],
            }
            fused = DETAILS.get("qa_e2e_7b_int8_fused", {})
            if "p50_ms" in fused:
                DETAILS["fused_ab_7b"] = {
                    "classic_p50_ms": round(p50c, 2),
                    "fused_p50_ms": fused["p50_ms"],
                    "context": (
                        "EQUAL both paths: 3 x 60-120-token pool chunks"
                    ),
                    "speculative_k": k_eng,
                }

        def sec_spec4():
            if "p50_ms" not in DETAILS.get("qa_e2e_7b_int8", {}):
                # classic section skipped/failed: recording a lone k=4
                # attempt inside its entry would violate the schema
                # PERF.md documents — use a standalone key instead
                target = DETAILS.setdefault("qa_e2e_7b_int8_spec4_only", {})
            else:
                target = None
            eng = GenerateEngine(
                cfg7,
                GenerateConfig(
                    max_new_tokens=64,
                    prefill_buckets=(512, 1024),
                    speculative_k=4,
                ),
                params=S["params8"],
            )
            try:
                p50b, p95b = measure_e2e(
                    eng, q_texts[2 : 2 + n_e2e], "7B-int8 spec_k=4"
                )
            finally:
                del eng
                gc.collect()
            rec = {
                "speculative_k": 4,
                "p50_ms": round(p50b, 2),
                "p95_ms": round(p95b, 2),
            }
            if target is not None:
                target.update(rec)
            else:
                DETAILS["qa_e2e_7b_int8"]["attempts"].append(rec)

        def sec_load_7b():
            from docqa_tpu.runtime.metrics import DEFAULT_REGISTRY as _REG

            hist = _REG.histogram("serve_tokens_per_chunk")
            count0 = hist.count
            sum0 = (hist.mean * count0) if count0 else 0.0
            load_engine = GenerateEngine(
                cfg7,
                GenerateConfig(
                    max_new_tokens=64,
                    prefill_buckets=(128, 512),
                    speculative_k=8,
                ),
                params=S["params8"],
            )
            try:
                # closed-loop grid, widened per VERDICT r4 item 3:
                # (32,32) was the r04 winner; 48-slot points probe whether
                # more lanes per weight-read push past the 9.3 plateau
                DETAILS["rag_load_7b_int8"] = sweep_load(
                    load_engine, 64, 512,
                    ((32, 32), (48, 32), (32, 16), (48, 16)),
                )
                DETAILS["rag_load_7b_int8"]["speculative_k"] = 8
                d_count = hist.count - count0
                DETAILS["rag_load_7b_int8"]["serve_tokens_per_chunk_mean"] = (
                    round((hist.mean * hist.count - sum0) / d_count, 2)
                    if d_count > 0
                    else None
                )
                log(f"config5b 7B-int8 closed load: {DETAILS['rag_load_7b_int8']}")
                bk = DETAILS["rag_load_7b_int8"]["best_knobs"]
                if remaining() > 180:
                    DETAILS["rag_load_7b_open16"] = run_open_loop(
                        load_engine, bk["n_slots"], bk["chunk"], 1024,
                        qps_target=16, n_req=96,
                    )
                    log(
                        f"config5b 7B-int8 OPEN loop @16: "
                        f"{DETAILS['rag_load_7b_open16']}"
                    )
                else:
                    DETAILS.setdefault("skipped", {})["load_7b_open16"] = (
                        f"budget: {remaining():.0f}s left"
                    )
            finally:
                del load_engine
                gc.collect()

        # rising-cost, falling-value order: the A/B comparator and the
        # load sections carry the round's claims; the spec-4 comparator
        # is a nice-to-have that must not displace them in the budget
        run_section("decode_7b_int8", sec_decode_7b, 90)
        run_section("e2e_7b_classic", sec_classic_7b, 150)
        run_section("load_7b", sec_load_7b, 300)
        run_section("e2e_7b_spec4", sec_spec4, 150)
        dispatch_health("after_7b_sections")
        # free the 7B tree before the 1.1B / IVF / bf16 sections need HBM
        S["gen8"] = S["params8"] = None
        gc.collect()

    # ---- 1.1B class (round-over-round comparability) ------------------------
    def sec_1b():
        gen_bf = GenerateEngine(dec_cfg, mesh=mesh)
        p50b, p95b = measure_e2e(gen_bf, q_texts[2:7], "1.1B bf16")
        DETAILS["qa_e2e_bf16"] = {
            "p50_ms": round(p50b, 2),
            "p95_ms": round(p95b, 2),
            "new_tokens": max_new,
            "decoder": f"{dec_cfg.hidden_dim}x{dec_cfg.num_layers}",
        }
        measure_decode(gen_bf, "decode_1b", "config3a bf16")
        del gen_bf
        gc.collect()
        if S["gen1"] is None:
            S["gen1"] = GenerateEngine(
                dataclasses.replace(dec_cfg, quantize_weights=True), mesh=mesh
            )
        if "qa_e2e" not in DETAILS:
            p50i, p95i = measure_e2e(
                S["gen1"], q_texts[2 : 2 + n_e2e], "1.1B int8"
            )
            DETAILS["qa_e2e"] = {
                "p50_ms": round(p50i, 2),
                "p95_ms": round(p95i, 2),
                "new_tokens": max_new,
                "decoder": f"{dec_cfg.hidden_dim}x{dec_cfg.num_layers}-int8",
            }
        measure_decode(S["gen1"], "decode_1b_int8", "config3a int8")
        measure_fused(S["gen1"], "qa_e2e_fused")

    def sec_load_1b():
        if S["gen1"] is None:  # e2e_1b skipped on budget
            S["gen1"] = GenerateEngine(
                dataclasses.replace(dec_cfg, quantize_weights=True), mesh=mesh
            )
        gen1 = S["gen1"]
        n_req = 64 if not small else 8
        cache_len = 1024 if not small else 256
        DETAILS["rag_load"] = sweep_load(
            gen1, n_req, cache_len, ((32, 16), (16, 16), (32, 32))
        )
        if not small and DETAILS["rag_load"]["sustained_qps"] < 20:
            # speculation at the winner: each batcher chunk verifies
            # spec_k draft tokens per slot in one weight read
            bk = DETAILS["rag_load"]["best_knobs"]
            for spec_k in (4,):
                gen_spec = GenerateEngine(
                    dataclasses.replace(dec_cfg, quantize_weights=True),
                    GenerateConfig(speculative_k=spec_k),
                    mesh=mesh,
                    params=gen1.params,
                )
                try:
                    qs, ws, ls, _tr, _tl = run_load(
                        gen_spec, bk["n_slots"], bk["chunk"], n_req, cache_len
                    )
                finally:
                    del gen_spec
                    gc.collect()
                DETAILS["rag_load"]["attempts"].append(
                    {**bk, "speculative_k": spec_k, "qps": round(qs, 2)}
                )
                if qs > DETAILS["rag_load"]["sustained_qps"]:
                    DETAILS["rag_load"].update(
                        sustained_qps=round(qs, 2),
                        wall_s=round(ws, 2),
                        request_p50_ms=round(float(np.percentile(ls, 50)), 1),
                        request_p95_ms=round(float(np.percentile(ls, 95)), 1),
                        best_knobs={**bk, "speculative_k": spec_k},
                    )
        log(f"config5 1.1B closed load: {DETAILS['rag_load']}")
        if not small and remaining() > 150:
            bk = DETAILS["rag_load"]["best_knobs"]
            spec_k = bk.get("speculative_k", 0)
            open_engine = (
                GenerateEngine(
                    dataclasses.replace(dec_cfg, quantize_weights=True),
                    GenerateConfig(
                        speculative_k=spec_k, prefill_buckets=(128, 512)
                    ),
                    mesh=mesh,
                    params=gen1.params,
                )
                if spec_k
                else gen1
            )
            try:
                DETAILS["rag_load_open16"] = run_open_loop(
                    open_engine, bk["n_slots"], bk["chunk"], 1024,
                    qps_target=16, n_req=96,
                )
                log(f"config5 1.1B OPEN loop @16: {DETAILS['rag_load_open16']}")
            finally:
                if open_engine is not gen1:
                    del open_engine
                    gc.collect()

    def sec_trace_overhead():
        """Tracing-overhead A/B on the qa_e2e path (acceptance: ≤2% on
        p50).  Same engine, same queries, recorder OFF then ON with a
        full per-request trace — the difference is what docqa-trace
        costs a served request."""
        from docqa_tpu import obs

        if S["gen1"] is None:
            S["gen1"] = GenerateEngine(
                dataclasses.replace(dec_cfg, quantize_weights=True), mesh=mesh
            )
        ask = make_ask(S["gen1"])
        for q in q_texts[:2]:  # compile at the measured shapes
            ask(q)
        n_ab = max(n_e2e, 8)
        queries = [q_texts[2 + i % n_queries] for i in range(n_ab)]

        def run_p50(traced: bool) -> float:
            lats = []
            for q in queries:
                t0 = time.perf_counter()
                if traced:
                    ctx = obs.new_trace("overhead_ask")
                    obs.call_in(ctx, ask, q)
                    obs.finish(ctx)
                else:
                    ask(q)
                lats.append((time.perf_counter() - t0) * 1e3)
            return float(np.percentile(lats, 50))

        was_enabled = obs.enabled()
        try:
            obs.set_enabled(False)
            p50_off = run_p50(False)
            obs.set_enabled(True)
            p50_on = run_p50(True)
        finally:
            obs.set_enabled(was_enabled)
        overhead = (p50_on - p50_off) / p50_off * 100.0 if p50_off else 0.0
        DETAILS["tracing_overhead"] = {
            "qa_e2e_p50_off_ms": round(p50_off, 2),
            "qa_e2e_p50_on_ms": round(p50_on, 2),
            "overhead_pct": round(overhead, 2),
            "samples": n_ab,
            "budget_pct": 2.0,
        }
        log(
            f"tracing overhead: p50 {p50_off:.1f}ms untraced -> "
            f"{p50_on:.1f}ms traced ({overhead:+.2f}%, budget 2%)"
        )

    def sec_telemetry_overhead():
        """Sampler + rollup overhead A/B on the qa_e2e path, protocol
        identical to sec_trace_overhead (acceptance: ≤2% on p50).  OFF =
        no sampler thread; ON = a sampler at the serving default cadence
        scraping registry + engine while the same queries run.  The
        histogram windowed-digest cost rides BOTH arms (it replaced the
        old reservoir unconditionally), so the delta isolates what the
        background scrape itself costs a served request."""
        from docqa_tpu import obs
        from docqa_tpu.runtime.metrics import DEFAULT_REGISTRY

        if S["gen1"] is None:
            S["gen1"] = GenerateEngine(
                dataclasses.replace(dec_cfg, quantize_weights=True), mesh=mesh
            )
        ask = make_ask(S["gen1"])
        for q in q_texts[:2]:  # compile at the measured shapes
            ask(q)
        n_ab = max(n_e2e, 8)
        queries = [q_texts[2 + i % n_queries] for i in range(n_ab)]

        def run_p50() -> float:
            lats = []
            for q in queries:
                t0 = time.perf_counter()
                ask(q)
                lats.append((time.perf_counter() - t0) * 1e3)
            return float(np.percentile(lats, 50))

        # the bench-wide sampler (main() top) scrapes this same registry
        # at 2 s cadence — it must be PAUSED for the OFF arm or the A/B
        # measures "one sampler vs two", not "none vs the serving
        # default".  The restart rides the finally so an exception
        # ANYWHERE in the section (run_section swallows them) cannot
        # leave the rest of the bench without its telemetry snapshot.
        sampler = None
        _bench_sampler.stop()
        try:
            p50_off = run_p50()
            tstore = obs.TelemetryStore(interval_s=1.0, points=600)
            sampler = obs.TelemetrySampler(
                tstore,
                registry=DEFAULT_REGISTRY,
                engine=S["gen1"],
                sample_every_s=2.0,  # the serving default cadence
                hbm_refresh_s=0,  # the AOT probe is a boot-time cost,
                # not a steady-state one — excluded like compiles are
            ).start()
            p50_on = run_p50()
        finally:
            if sampler is not None:
                sampler.stop()
            _bench_sampler.start()
        overhead = (p50_on - p50_off) / p50_off * 100.0 if p50_off else 0.0
        DETAILS["telemetry_overhead"] = {
            "qa_e2e_p50_off_ms": round(p50_off, 2),
            "qa_e2e_p50_on_ms": round(p50_on, 2),
            "overhead_pct": round(overhead, 2),
            "samples": n_ab,
            "sampler_ticks": sampler.ticks,
            "budget_pct": 2.0,
            "within_budget": overhead <= 2.0,
        }
        log(
            f"telemetry overhead: p50 {p50_off:.1f}ms unsampled -> "
            f"{p50_on:.1f}ms sampled ({overhead:+.2f}%, budget 2%)"
        )

    def sec_dispatch_overhead():
        """Dispatch-spine overhead A/B on the qa_e2e path, protocol
        identical to sec_telemetry_overhead (acceptance: <= 2% on p50).
        OFF = spine inline mode (work items execute on the submitting
        thread — the pre-spine dispatch economics); ON = the serving
        default (items hop to a bounded lane).  The delta isolates what
        the lane handoff costs a served request."""
        from docqa_tpu.engines.spine import get_spine

        if S["gen1"] is None:
            S["gen1"] = GenerateEngine(
                dataclasses.replace(dec_cfg, quantize_weights=True), mesh=mesh
            )
        ask = make_ask(S["gen1"])
        for q in q_texts[:2]:  # compile at the measured shapes
            ask(q)
        n_ab = max(n_e2e, 8)
        queries = [q_texts[2 + i % n_queries] for i in range(n_ab)]

        def run_p50() -> float:
            lats = []
            for q in queries:
                t0 = time.perf_counter()
                ask(q)
                lats.append((time.perf_counter() - t0) * 1e3)
            return float(np.percentile(lats, 50))

        spine = get_spine()
        was_inline = spine.stats()["inline"]  # restore the SESSION mode
        try:
            spine.reconfigure(inline=True)
            p50_inline = run_p50()
        finally:
            spine.reconfigure(inline=False)
        try:
            p50_spine = run_p50()
        finally:
            spine.reconfigure(inline=was_inline)
        overhead = (
            (p50_spine - p50_inline) / p50_inline * 100.0
            if p50_inline
            else 0.0
        )
        DETAILS["dispatch_overhead"] = {
            "qa_e2e_p50_inline_ms": round(p50_inline, 2),
            "qa_e2e_p50_spine_ms": round(p50_spine, 2),
            "overhead_pct": round(overhead, 2),
            "samples": n_ab,
            "n_lanes": spine.stats()["n_lanes"],
            "budget_pct": 2.0,
            "within_budget": overhead <= 2.0,
        }
        log(
            f"dispatch-spine overhead: p50 {p50_inline:.1f}ms inline -> "
            f"{p50_spine:.1f}ms spine ({overhead:+.2f}%, budget 2%)"
        )

    def run_pool_load(engine, replicas, n_slots, chunk, n_req, cache_len):
        """Closed-loop burst through an ``EnginePool`` with N replicas —
        the aggregate-QPS-vs-replica-count measurement ROADMAP item 5
        names.  Same protocol as :func:`run_load` so the 1-replica row is
        directly comparable to ``rag_load`` (pool dispatch overhead =
        the delta)."""
        import threading as _threading

        from docqa_tpu.engines.pool import EnginePool

        pool = EnginePool(
            engine,
            replicas=replicas,
            n_slots=n_slots,
            chunk=chunk,
            cache_len=cache_len,
            # no canary/hedge noise inside the measured window; health
            # checks stay on (they are part of the serving config)
            canary_interval_s=600.0,
            health_interval_s=0.2,
        )
        try:
            pool.warmup(buckets=engine.gen.prefill_buckets[:1])
            # one replica's cost models cover the pool (shared programs)
            pool.annotate_costs()
            prompt_ids = [
                [7 + i % 13, 5, 9, 11, 3 + i % 7] for i in range(n_req)
            ]
            # touch every replica's admission shapes before t0
            for h in [
                pool.submit_ids(p, max_new_tokens=4)
                for p in prompt_ids[: n_slots * replicas]
            ]:
                h.result()
            pool.submit_ids(prompt_ids[0], max_new_tokens=max_new).result()
            # per-request success, same as run_open_loop: a failed
            # request must not leave a 0.0 placeholder dragging the
            # percentiles down, nor count toward achieved QPS
            lat_ms = [None] * n_req
            waiters = []
            dispatch_fin = dispatch_window()
            t0 = time.perf_counter()

            def wait_one(idx, handle):
                try:
                    handle.result()
                except Exception as e:
                    log(f"pool_scaling request {idx} failed: {e!r}")
                    return
                lat_ms[idx] = (time.perf_counter() - t0) * 1e3

            for i, p in enumerate(prompt_ids):
                h = pool.submit_ids(p, max_new_tokens=max_new)
                w = _threading.Thread(target=wait_one, args=(i, h))
                w.start()
                waiters.append(w)
            for w in waiters:
                w.join()
            wall = time.perf_counter() - t0
            dispatch = dispatch_fin(wall)
        finally:
            pool.stop()
            del pool
            gc.collect()
        ok = [v for v in lat_ms if v is not None]
        return len(ok) / wall, wall, ok, n_req - len(ok), dispatch

    def sec_pool_scaling():
        """Aggregate QPS + p50/p95 at 1, 2, 4 pool replicas (ROADMAP
        item 5's scale-out benchmark).  HONESTY (r05 rule): replicas
        here are same-host lanes SHARING one device, so this measures
        pool dispatch overhead and failover-ready replication — NOT
        per-slice hardware scaling; linear aggregate QPS needs one mesh
        slice per replica (labeled accordingly)."""
        if S["gen1"] is None:
            S["gen1"] = GenerateEngine(
                dataclasses.replace(dec_cfg, quantize_weights=True), mesh=mesh
            )
        gen1 = S["gen1"]
        n_req = 32 if not small else 8
        cache_len = 1024 if not small else 256
        n_slots = 8 if not small else 4
        rows = []
        for replicas in (1, 2, 4):
            if remaining() < 60 and rows:
                log(f"pool_scaling: budget stop before {replicas} replicas")
                break
            try:
                qps, wall, lat, errors, dispatch = run_pool_load(
                    gen1, replicas, n_slots, 16, n_req, cache_len
                )
            except Exception as e:
                log(f"pool_scaling at {replicas} replicas failed: {e!r}")
                continue
            if not lat:
                log(f"pool_scaling at {replicas} replicas: 0 completions")
                continue
            rows.append(
                {
                    "replicas": replicas,
                    "aggregate_qps": round(qps, 2),
                    "wall_s": round(wall, 2),
                    "request_p50_ms": round(float(np.percentile(lat, 50)), 1),
                    "request_p95_ms": round(float(np.percentile(lat, 95)), 1),
                    "requests_ok": len(lat),
                    "errors": errors,
                    # spine-sourced: how much of the wall the device
                    # worked, and FLOPs-based MFU — honest evidence that
                    # same-host replicas share ONE device's time
                    "mfu": (dispatch or {}).get("mfu"),
                    "device_time_share": (
                        (dispatch or {}).get("device_time_share")
                    ),
                    "dispatch": dispatch,
                }
            )
            log(
                "pool_scaling: "
                f"{ {k: v for k, v in rows[-1].items() if k != 'dispatch'} }"
            )
        kv = None
        if S["gen1"] is not None:
            from docqa_tpu.engines.paged import kv_bytes_per_token

            kv = {
                "bytes_per_token": kv_bytes_per_token(S["gen1"].cfg),
                "note": (
                    "per-replica paged block pools; per-token HBM at "
                    "block granularity (see kv_paging for the fixed-HBM "
                    "n_slots frontier)"
                ),
            }
        DETAILS["pool_scaling"] = {
            "arrival": "closed-loop burst",
            "requests": n_req,
            "n_slots_per_replica": n_slots,
            "kv": kv,
            "placement": (
                "same-host lanes, one shared device — dispatch overhead "
                "and replication cost, not per-slice hardware scaling"
                + ("" if on_tpu else " (CPU smoke)")
            ),
            "rows": rows,
        }

    def sec_kv_paging():
        """The r04 ``n_slots`` knob sweep RE-RUN under paged KV at FIXED
        KV HBM (ROADMAP item 1's before/after evidence).  r04's best was
        18.3 QPS at n_slots=32 with the bucket-padded slot model, where
        every slot pinned worst-case-bucket HBM for its lifetime; here
        the pool is pinned to the HBM 16 worst-case slots would have
        taken, and the sweep shows how many MORE slots the same bytes
        sustain when blocks free at retirement — per-token KV bytes and
        block-pool occupancy are first-class columns."""
        if S["gen1"] is None:
            S["gen1"] = GenerateEngine(
                dataclasses.replace(dec_cfg, quantize_weights=True), mesh=mesh
            )
        gen1 = S["gen1"]
        cache_len = 1024 if not small else 256
        n_req = 48 if not small else 8
        # fix the pool at 16 worst-case sequences' worth of KV — the
        # HBM the OLD model needed for n_slots=16 — and sweep the slot
        # count PAST what that HBM could previously hold
        base_slots = 16 if not small else 2
        fixed_pool_tokens = base_slots * cache_len
        sweep = (16, 32, 48) if not small else (2, 4)
        from docqa_tpu.runtime.metrics import DEFAULT_REGISTRY as _REG

        rows = []
        for ns in sweep:
            if remaining() < 60 and rows:
                log(f"kv_paging: budget stop before n_slots={ns}")
                break
            shed0 = _REG.counter("serve_block_shed").value
            try:
                qps, wall, lat, _traces, telem = run_load(
                    gen1, ns, 16, n_req, cache_len,
                    kv_pool_tokens=fixed_pool_tokens,
                )
            except Exception as e:
                log(f"kv_paging at n_slots={ns} failed: {e!r}")
                continue
            if not lat:
                continue
            kv = telem.get("kv") or {}
            rows.append(
                {
                    "n_slots": ns,
                    "sustained_qps": round(qps, 2),
                    "request_p50_ms": round(float(np.percentile(lat, 50)), 1),
                    "request_p95_ms": round(float(np.percentile(lat, 95)), 1),
                    "kv_peak_blocks_used": kv.get("peak_blocks_used"),
                    "kv_peak_bytes": kv.get("peak_kv_bytes"),
                    "kv_peak_utilization": kv.get("peak_utilization"),
                    # overcommit honesty: typed pool-exhaustion sheds
                    # during this run (0 = the fixed pool truly held
                    # this slot count)
                    "block_sheds": int(
                        _REG.counter("serve_block_shed").value - shed0
                    ),
                }
            )
            log(f"kv_paging: {rows[-1]}")
        from docqa_tpu.engines.paged import kv_bytes_per_token

        bpt = kv_bytes_per_token(gen1.cfg)
        best = max(rows, key=lambda r: r["sustained_qps"]) if rows else None
        DETAILS["kv_paging"] = {
            "arrival": "closed-loop burst",
            "requests": n_req,
            "fixed_pool_tokens": fixed_pool_tokens,
            "fixed_pool_bytes": fixed_pool_tokens * bpt,
            "bytes_per_token": bpt,
            "n_slots_sweep": rows,
            "best": best,
            "reference_r04": {
                "best_qps": 18.3,
                "n_slots": 32,
                "model": (
                    "bucket-padded slot model: per-slot worst-case-bucket "
                    "HBM pinned for the slot's lifetime (BENCH_r04)"
                ),
            },
        }
        if best:
            log(
                f"kv_paging: best {best['sustained_qps']} QPS at "
                f"n_slots={best['n_slots']} with the pool fixed at "
                f"{fixed_pool_tokens} KV tokens "
                f"({fixed_pool_tokens * bpt / 1e6:.1f} MB)"
            )

    def sec_prefix_reuse():
        """Repeat-heavy session mix (docqa-prefix): M patients x Q
        consecutive questions, each patient's questions sharing one
        template+context prompt prefix — the clinical /ask pattern the
        prefix cache exists for.  The SAME mix runs twice through
        identical batcher knobs, sharing disabled then enabled; the
        headline is the sustained-QPS ratio plus the first-class
        warm_prefix_hit_rate / prefill_tokens_avoided columns (the
        ROADMAP done-bar: >= 2x on the repeat-heavy mix)."""
        if S["gen1"] is None:
            S["gen1"] = GenerateEngine(
                dataclasses.replace(dec_cfg, quantize_weights=True), mesh=mesh
            )
        gen1 = S["gen1"]
        cache_len = 1024 if not small else 256
        n_patients = 6 if not small else 2
        n_questions = 8 if not small else 3
        # shared context ~6 align units (768 tokens) + a short question
        # tail: the template+chunks shape of a real clinical /ask, long
        # enough that prefill dominates a cold admission (measured 2.1x
        # QPS on the CPU smoke model at this shape with max_new=64)
        ctx_len = 768 if not small else 160
        rng = np.random.default_rng(11)
        mix = []
        for pat in range(n_patients):
            ctx = (
                rng.integers(3, 120, size=ctx_len)
                .astype(int)
                .tolist()
            )
            for q in range(n_questions):
                tail = [7 + (pat * 13 + q * 5) % 90, 5, 9, 3 + q]
                mix.append((ctx + tail, f"bench-patient-{pat}"))
        knobs = dict(
            n_slots=8 if not small else 2, chunk=16 if not small else 4,
        )
        rows = {}
        for label, enabled in (("disabled", False), ("enabled", True)):
            qps, wall, lat, _traces, telem = run_load(
                gen1, knobs["n_slots"], knobs["chunk"], len(mix),
                cache_len, session_mix=mix, prefix_cache=enabled,
            )
            rows[label] = {
                "sustained_qps": round(qps, 2),
                "request_p50_ms": round(float(np.percentile(lat, 50)), 1),
                "request_p95_ms": round(float(np.percentile(lat, 95)), 1),
                **(telem.get("prefix") or {}),
            }
            log(f"prefix_reuse [{label}]: {rows[label]}")
        speedup = (
            rows["enabled"]["sustained_qps"]
            / max(rows["disabled"]["sustained_qps"], 1e-9)
        )
        DETAILS["prefix_reuse"] = {
            "arrival": "closed-loop burst (repeat-heavy session mix)",
            "patients": n_patients,
            "questions_per_patient": n_questions,
            "context_tokens": ctx_len,
            "requests": len(mix),
            **knobs,
            "sharing_disabled": rows["disabled"],
            "sharing_enabled": rows["enabled"],
            "warm_prefix_hit_rate": rows["enabled"]["warm_prefix_hit_rate"],
            "prefill_tokens_avoided": (
                rows["enabled"]["prefill_tokens_avoided"]
            ),
            "qps_speedup": round(speedup, 2),
            "qps_target_ratio": 2.0,
        }
        log(
            f"prefix_reuse: {rows['disabled']['sustained_qps']} -> "
            f"{rows['enabled']['sustained_qps']} QPS "
            f"({speedup:.2f}x) at warm hit rate "
            f"{rows['enabled']['warm_prefix_hit_rate']}"
        )

    def sec_cost_attribution():
        """Mixed-class serving window (docqa-costscope): interactive
        /ask-shaped shorts + batch summarize-shaped longs + background
        refresh driven CONCURRENTLY through one batcher whose KV pool is
        deliberately overcommitted.  Reports per-class device-ms, KV
        block-seconds, and shed counts; the per-class device-time sums
        are cross-checked against the spine's measured
        serve_prefill_fetch + serve_decode_chunk window (the share_sum
        column — acceptance wants ~1.0), and the induced
        BlockPoolExhausted shed's forensics snapshot must name the class
        holding the majority of blocks."""
        import threading as _threading

        from docqa_tpu import obs as _obs
        from docqa_tpu.engines.serve import ContinuousBatcher

        if S["gen1"] is None:
            S["gen1"] = GenerateEngine(
                dataclasses.replace(dec_cfg, quantize_weights=True), mesh=mesh
            )
        gen1 = S["gen1"]
        cache_len = 1024 if not small else 256
        ctx_len = 512 if not small else 128
        n_interactive = 12 if not small else 4
        n_batch = 4 if not small else 2
        n_background = 2
        n_slots = 6 if not small else 3
        # overcommit: the pool holds ~2 batch longs + margin, so the
        # concurrent mix must contend — the induced BlockPoolExhausted
        # shed (at submit or mid-decode growth) is the point
        pool_tokens = int(2.2 * (ctx_len + 96))
        ledger = _obs.DEFAULT_COST_LEDGER
        b = ContinuousBatcher(
            gen1, n_slots=n_slots, chunk=8, cache_len=cache_len,
            kv_pool_tokens=pool_tokens, max_queue=n_interactive // 2,
        )
        old_probe = ledger._pressure_probe
        try:
            ledger.set_pressure_probe(b.pressure_by_class)
            b.warmup(buckets=b.gen.prefill_buckets[:1])
            b.annotate_costs()
            b.submit_ids([5, 9, 11], max_new_tokens=4).result()
            rng = np.random.default_rng(3)
            before = ledger.class_totals()
            # the forensics ring is bounded and process-global: window
            # membership is by timestamp, never by index (an earlier
            # section may already have wrapped it)
            t_window0 = time.time()
            dispatch_fin = dispatch_window()
            errors: dict = {}
            lock = _threading.Lock()
            waiters = []
            t0 = time.perf_counter()

            def drive(handle_fn, idx, cls):
                try:
                    handle_fn().result(timeout=300)
                except Exception as e:
                    with lock:
                        errors.setdefault(cls, []).append(repr(e)[:80])

            # batch longs FIRST: they seize the pool's blocks, so the
            # interactive flood contends against batch-held HBM (the
            # "who caused the shed" scenario the forensics must answer)
            for i in range(n_batch):
                ctx = rng.integers(3, 120, size=ctx_len).astype(int).tolist()
                h = lambda p=ctx, i=i: b.submit_ids(
                    p, max_new_tokens=64, req_class="batch",
                    prefix_key=f"cost-batch-{i}",
                )
                w = _threading.Thread(target=drive, args=(h, i, "batch"))
                w.start()
                waiters.append(w)
            for i in range(n_background):
                h = lambda i=i: b.submit_ids(
                    [3 + i, 5, 9], max_new_tokens=4, req_class="background",
                )
                w = _threading.Thread(
                    target=drive, args=(h, i, "background")
                )
                w.start()
                waiters.append(w)
            for i in range(n_interactive):
                h = lambda i=i: b.submit_ids(
                    [7 + i % 13, 5, 9, 11, 3 + i % 7],
                    max_new_tokens=16, req_class="interactive",
                )
                w = _threading.Thread(
                    target=drive, args=(h, i, "interactive")
                )
                w.start()
                waiters.append(w)
            for w in waiters:
                w.join()
            wall = time.perf_counter() - t0
            dispatch = dispatch_fin(wall)
            bs = b.block_seconds()
        finally:
            ledger.set_pressure_probe(old_probe)
            b.stop()
            residual_after_stop = b.block_seconds()["residual"]
            del b
            gc.collect()
        after = ledger.class_totals()
        per_class = {}
        attributed_ms = 0.0
        for cls in ("interactive", "batch", "background"):
            a, bf = after.get(cls, {}), before.get(cls, {})

            def d(key):
                return a.get(key, 0.0) - bf.get(key, 0.0)

            dev = sum(
                d(k) for k in (
                    "prefill_device_ms_cold", "prefill_device_ms_warm",
                    "decode_device_ms",
                )
            )
            attributed_ms += dev
            per_class[cls] = {
                "requests": int(d("requests")),
                "device_ms": round(dev, 2),
                "kv_block_seconds": round(d("kv_block_seconds"), 4),
                "decode_tokens": int(d("decode_tokens")),
                "queue_wait_ms": round(d("queue_wait_ms"), 2),
            }
        spine_ms = sum(
            row["device_ms"]
            for name, row in dispatch["stages"].items()
            if name in ("serve_prefill_fetch", "serve_decode_chunk")
        )
        share_sum = attributed_ms / spine_ms if spine_ms else None
        new_sheds = [
            s for s in ledger.sheds() if s["t_unix"] >= t_window0
        ]
        block_sheds = [
            s for s in new_sheds if s["kind"] == "block_pool_exhausted"
        ]
        forensic = block_sheds[-1] if block_sheds else (
            new_sheds[-1] if new_sheds else None
        )
        DETAILS["cost_attribution"] = {
            "arrival": "concurrent mixed-class burst",
            "pool_tokens": pool_tokens,
            "per_class": per_class,
            "errors": {k: len(v) for k, v in errors.items()},
            "attributed_device_ms": round(attributed_ms, 2),
            "spine_serve_device_ms": round(spine_ms, 2),
            # acceptance: ~1.0 — the ledger partitions exactly the
            # measured fetch values, so any gap is untraced traffic
            # (canaries/warmup), not double counting.  `is not None`:
            # an exactly-0.0 sum is a broken-attribution signal that
            # must PRINT as 0.0, never masquerade as no-window
            "share_sum": (
                round(share_sum, 4) if share_sum is not None else None
            ),
            "kv_block_seconds_window": round(bs["billed"], 4),
            "kv_residual_after_stop": round(residual_after_stop, 6),
            "sheds_in_window": len(new_sheds),
            "block_pool_sheds": len(block_sheds),
            "forensics_example": forensic,
            "majority_block_class": (
                (forensic or {}).get("majority_block_class")
            ),
        }
        log(
            f"cost_attribution: per-class {per_class}; share_sum="
            f"{DETAILS['cost_attribution']['share_sum']} "
            f"(attributed {attributed_ms:.0f}ms of {spine_ms:.0f}ms "
            f"spine serve); {len(block_sheds)} BlockPoolExhausted "
            f"shed(s), majority holder "
            f"{DETAILS['cost_attribution']['majority_block_class']}; "
            f"kv residual {residual_after_stop:.2e}"
        )

    def sec_cost_overhead():
        """Cost-ledger overhead A/B on the qa_e2e path, protocol
        identical to sec_dispatch_overhead (acceptance: <= 2% on p50).
        OFF = ledger disabled (open() returns None, every accounting
        site short-circuits on the None guard); ON = the serving
        default.  The delta isolates what per-request cost attribution
        costs a served request."""
        from docqa_tpu import obs as _obs

        if S["gen1"] is None:
            S["gen1"] = GenerateEngine(
                dataclasses.replace(dec_cfg, quantize_weights=True), mesh=mesh
            )
        ask = make_ask(S["gen1"])
        for q in q_texts[:2]:  # compile at the measured shapes
            ask(q)
        n_ab = max(n_e2e, 8)
        queries = [q_texts[2 + i % n_queries] for i in range(n_ab)]

        def run_p50() -> float:
            lats = []
            for q in queries:
                t0 = time.perf_counter()
                ask(q)
                lats.append((time.perf_counter() - t0) * 1e3)
            return float(np.percentile(lats, 50))

        ledger = _obs.DEFAULT_COST_LEDGER
        try:
            ledger.set_enabled(False)
            p50_off = run_p50()
        finally:
            ledger.set_enabled(True)
        p50_on = run_p50()
        overhead = (p50_on - p50_off) / p50_off * 100.0 if p50_off else 0.0
        DETAILS["cost_overhead"] = {
            "qa_e2e_p50_off_ms": round(p50_off, 2),
            "qa_e2e_p50_on_ms": round(p50_on, 2),
            "overhead_pct": round(overhead, 2),
            "samples": n_ab,
            "budget_pct": 2.0,
            "within_budget": overhead <= 2.0,
        }
        log(
            f"cost-ledger overhead: p50 {p50_off:.1f}ms off -> "
            f"{p50_on:.1f}ms on ({overhead:+.2f}%, budget 2%)"
        )

    def sec_qos_overload():
        """Multi-tenant QoS A/B (docqa-qos): the cost_attribution
        mixed-class overload replayed twice through overcommitted
        batchers — policy OFF (plain FIFO, the pre-QoS behavior) vs ON
        (weighted-fair admission + KV preemption + burn-driven batch
        deferral).  Acceptance: the ON arm's interactive p95 holds the
        SLO (anchored at 5x the unloaded interactive median) while
        batch degrades gracefully — deferred/preempted, not lost, with
        nonzero goodput and zero KV residual in both arms."""
        import threading as _threading

        from docqa_tpu import obs as _obs
        from docqa_tpu.config import QoSConfig
        from docqa_tpu.engines.serve import ContinuousBatcher

        if S["gen1"] is None:
            S["gen1"] = GenerateEngine(
                dataclasses.replace(dec_cfg, quantize_weights=True), mesh=mesh
            )
        gen1 = S["gen1"]
        cache_len = 1024 if not small else 256
        ctx_len = 512 if not small else 128
        n_interactive = 12 if not small else 6
        n_batch = 4 if not small else 3
        n_slots = 6 if not small else 3
        # same overcommit as cost_attribution: ~2 batch longs fill the
        # pool, so interactive admission must contend for blocks — the
        # exact pressure the preemption policy exists to resolve
        pool_tokens = int(2.2 * (ctx_len + 96))
        ledger = _obs.DEFAULT_COST_LEDGER
        reg = _obs.DEFAULT_REGISTRY
        slo_anchor: dict = {}

        def run_arm(qos):
            b = ContinuousBatcher(
                gen1, n_slots=n_slots, chunk=8, cache_len=cache_len,
                kv_pool_tokens=pool_tokens, qos=qos,
            )
            lats: dict = {"interactive": [], "batch": []}
            errors: dict = {}
            lock = _threading.Lock()
            # synthetic burn probe: flipped true once contended
            # interactive latency crosses the SLO, so the deferral path
            # runs against a REAL policy decision (the production probe
            # is BurnRateEvaluator.firing; the bench has no HTTP layer)
            burning = [False]
            if qos is not None:
                b.set_slo_probe(
                    lambda: ["ask_p95_latency"] if burning[0] else []
                )
            before = ledger.class_totals()
            c0 = {
                k: reg.counter(k).value
                for k in ("qos_preempted", "qos_deferred")
            }
            try:
                b.warmup(buckets=b.gen.prefill_buckets[:1])
                # unloaded interactive reference: the SLO anchor (first
                # arm only, shared so both arms gate against one number)
                if "slo_ms" not in slo_anchor:
                    solo = []
                    for i in range(3):
                        t0 = time.perf_counter()
                        b.submit_ids(
                            [7 + i, 5, 9, 11], max_new_tokens=16,
                            req_class="interactive",
                        ).result(timeout=120)
                        solo.append((time.perf_counter() - t0) * 1e3)
                    slo_anchor["solo_ms"] = float(np.median(solo))
                    slo_anchor["slo_ms"] = 5.0 * slo_anchor["solo_ms"]
                slo_ms = slo_anchor["slo_ms"]
                rng = np.random.default_rng(11)
                waiters = []
                t0 = time.perf_counter()

                def drive(handle_fn, cls):
                    t_req = time.perf_counter()
                    try:
                        handle_fn().result(timeout=300)
                        ms = (time.perf_counter() - t_req) * 1e3
                        with lock:
                            lats[cls].append(ms)
                            if cls == "interactive" and ms > slo_ms:
                                burning[0] = True
                    except Exception as e:
                        with lock:
                            errors.setdefault(cls, []).append(repr(e)[:80])

                # batch longs first: they seize the pool before the
                # interactive flood arrives (cost_attribution's shape)
                for i in range(n_batch):
                    ctx = (
                        rng.integers(3, 120, size=ctx_len).astype(int)
                        .tolist()
                    )
                    h = lambda p=ctx: b.submit_ids(
                        p, max_new_tokens=64, req_class="batch",
                    )
                    w = _threading.Thread(target=drive, args=(h, "batch"))
                    w.start()
                    waiters.append(w)
                time.sleep(0.05)  # let batch reach the slots first
                for i in range(n_interactive):
                    h = lambda i=i: b.submit_ids(
                        [7 + i % 13, 5, 9, 11, 3 + i % 7],
                        max_new_tokens=16, req_class="interactive",
                    )
                    w = _threading.Thread(
                        target=drive, args=(h, "interactive")
                    )
                    w.start()
                    waiters.append(w)
                    time.sleep(0.01)  # open-loop-ish arrival spacing
                for w in waiters:
                    w.join()
                wall = time.perf_counter() - t0
            finally:
                b.stop()
                residual = b.block_seconds()["residual"]
                del b
                gc.collect()
            after = ledger.class_totals()

            def d(cls, key):
                return after.get(cls, {}).get(key, 0.0) - before.get(
                    cls, {}
                ).get(key, 0.0)

            ia = lats["interactive"]
            return {
                "interactive_p50_ms": (
                    round(float(np.percentile(ia, 50)), 2) if ia else None
                ),
                "interactive_p95_ms": (
                    round(float(np.percentile(ia, 95)), 2) if ia else None
                ),
                "interactive_completed": len(ia),
                "batch_completed": len(lats["batch"]),
                "batch_goodput_tok_s": round(
                    d("batch", "decode_tokens") / wall, 2
                ),
                "batch_preempted_block_seconds": round(
                    d("batch", "preempted_block_seconds"), 4
                ),
                "preempted": int(
                    reg.counter("qos_preempted").value - c0["qos_preempted"]
                ),
                "deferred": int(
                    reg.counter("qos_deferred").value - c0["qos_deferred"]
                ),
                "errors": {k: len(v) for k, v in errors.items()},
                "kv_residual_after_stop": round(residual, 6),
                "wall_s": round(wall, 2),
            }

        arm_off = run_arm(None)
        arm_on = run_arm(
            QoSConfig(preemption="on", aging_floor_s=2.0)
        )
        slo_ms = slo_anchor["slo_ms"]
        p95_on = arm_on["interactive_p95_ms"]
        p95_off = arm_off["interactive_p95_ms"]
        DETAILS["qos_overload"] = {
            "arrival": "batch longs first, paced interactive flood",
            "pool_tokens": pool_tokens,
            "interactive_slo_ms": round(slo_ms, 2),
            "interactive_solo_ms": round(slo_anchor["solo_ms"], 2),
            "off": arm_off,
            "on": arm_on,
            # acceptance: policy-on interactive p95 holds the SLO while
            # batch still makes progress (degrades, is not starved)
            "on_holds_slo": bool(
                p95_on is not None and p95_on <= slo_ms
            ),
            "batch_survives": bool(
                arm_on["batch_completed"] + arm_on["deferred"]
                >= n_batch
            ),
        }
        log(
            f"qos_overload: interactive p95 {p95_off}ms (off) -> "
            f"{p95_on}ms (on) vs SLO {slo_ms:.0f}ms; on-arm batch "
            f"goodput {arm_on['batch_goodput_tok_s']} tok/s, "
            f"{arm_on['preempted']} preemption(s), "
            f"{arm_on['deferred']} deferral(s); residual "
            f"off={arm_off['kv_residual_after_stop']:.2e} "
            f"on={arm_on['kv_residual_after_stop']:.2e}"
        )

    run_section("e2e_1b", sec_1b, 240)
    run_section("load_1b", sec_load_1b, 200)
    run_section("pool_scaling", sec_pool_scaling, 150)
    run_section("kv_paging", sec_kv_paging, 180)
    run_section("prefix_reuse", sec_prefix_reuse, 150)
    run_section("cost_attribution", sec_cost_attribution, 150)
    run_section("trace_overhead", sec_trace_overhead, 90)
    run_section("telemetry_overhead", sec_telemetry_overhead, 90)
    run_section("dispatch_overhead", sec_dispatch_overhead, 60)
    run_section("cost_overhead", sec_cost_overhead, 60)
    run_section("qos_overload", sec_qos_overload, 150)

    # ---- config 4: summarizer, 5 retrieved chunks ---------------------------
    docs = [
        (f"doc{i}", f"Patient note {i}: " + "stable vitals observed. " * 40)
        for i in range(5)
    ]

    def sec_summarize():
        from docqa_tpu.engines.summarize import SummarizeEngine

        if S["gen1"] is None:  # e2e_1b skipped on budget
            S["gen1"] = GenerateEngine(
                dataclasses.replace(dec_cfg, quantize_weights=True), mesh=mesh
            )
        summ = SummarizeEngine(S["gen1"], SummarizerConfig())
        summ.summarize_patient("p1", docs, max_tokens=32 if small else 128)
        t_summ, _ = timed(
            lambda: summ.summarize_patient(
                "p1", docs, max_tokens=32 if small else 128
            )
        )
        DETAILS["summarize"] = {"five_chunk_ms": round(t_summ * 1e3, 1)}
        log(f"config4 summarize (5 chunks): {t_summ*1e3:.0f}ms")
        del summ
        gc.collect()

    def sec_seq2seq():
        # config 4b: the dedicated BART-class encoder-decoder backend
        # (the architecture BASELINE config 4 actually names; greedy for
        # the timed run — the beam-4 program compiles for minutes at
        # bart-large depth and runs late)
        from docqa_tpu.config import Seq2SeqConfig
        from docqa_tpu.engines.seq2seq import Seq2SeqEngine
        from docqa_tpu.engines.summarize import SummarizeEngine

        s2s_cfg = (
            Seq2SeqConfig()
            if small
            else dataclasses.replace(
                Seq2SeqConfig.bart_large_cnn(),
                num_beams=1,
                min_length=0,
                no_repeat_ngram=0,
            )
        )
        s2s = Seq2SeqEngine(s2s_cfg)
        summ2 = SummarizeEngine(
            s2s,
            SummarizerConfig(max_input_tokens=s2s_cfg.max_src_len),
            instruction_prompts=False,
        )
        summ2.summarize_patient("p1", docs, max_tokens=16 if small else 128)
        t_s2s, _ = timed(
            lambda: summ2.summarize_patient(
                "p1", docs, max_tokens=16 if small else 128
            )
        )
        DETAILS["summarize_seq2seq"] = {
            "five_chunk_ms": round(t_s2s * 1e3, 1),
            "model": f"bart-class {s2s_cfg.d_model}x"
            f"{s2s_cfg.enc_layers}+{s2s_cfg.dec_layers}",
            "decode": "greedy",
        }
        log(f"config4b seq2seq summarize (5 chunks): {t_s2s*1e3:.0f}ms")
        del s2s, summ2
        gc.collect()
        if not small:

            def run_beam_late():
                # beam-4 with the full generation constraints — deferred:
                # the beam program's XLA compile at bart-large depth is
                # the risk (minutes), not its runtime
                try:
                    s2s_beam = Seq2SeqEngine(Seq2SeqConfig.bart_large_cnn())
                    summ_b = SummarizeEngine(
                        s2s_beam,
                        SummarizerConfig(max_input_tokens=s2s_cfg.max_src_len),
                        instruction_prompts=False,
                    )
                    t0 = time.perf_counter()
                    summ_b.summarize_patient("p1", docs, max_tokens=128)
                    compile_s = time.perf_counter() - t0
                    t_beam, _ = timed(
                        lambda: summ_b.summarize_patient(
                            "p1", docs, max_tokens=128
                        )
                    )
                    DETAILS["summarize_seq2seq_beam"] = {
                        "five_chunk_ms": round(t_beam * 1e3, 1),
                        "compile_s": round(compile_s, 1),
                        "num_beams": Seq2SeqConfig.bart_large_cnn().num_beams,
                    }
                    log(
                        f"config4b beam summarize (5 chunks): "
                        f"{t_beam*1e3:.0f}ms (compile {compile_s:.0f}s)"
                    )
                except Exception as e:
                    log(f"beam summarize bench failed: {e!r}")
                    DETAILS["summarize_seq2seq_beam"] = {"error": repr(e)[:300]}

            late_sections.append(("summarize_beam", run_beam_late, 360))

    run_section("summarize", sec_summarize, 90)
    run_section("summarize_seq2seq", sec_seq2seq, 180)

    # ---- config 2: deid NER throughput, batch = 32 --------------------------
    _ner_cache = os.path.join(
        os.path.expanduser("~"), ".cache", "docqa_tpu", "ner.npz"
    )

    def sec_deid():
        from docqa_tpu.deid.engine import DeidEngine

        if small:
            # random-init weights: identical FLOPs/memory to trained, and
            # the tagger architecture is what config 2 measures
            deid = DeidEngine(NERConfig(), use_ner_model=True)
        else:
            # trained weights via the cache; load_or_train runs any needed
            # training in a CHILD process
            os.makedirs(os.path.dirname(_ner_cache), exist_ok=True)
            deid = DeidEngine.trained(NERConfig(), params_path=_ner_cache)
        docs32 = [
            f"Patient {i} was admitted on 2024-03-{1 + i % 27:02d} with "
            "chest pain. " + "History reviewed with the care team. " * 20
            for i in range(32)
        ]
        deid.deidentify_batch(docs32)  # compile
        t_deid, _ = timed(lambda: deid.deidentify_batch(docs32), n=3)
        DETAILS["deid"] = {
            "batch32_ms": round(t_deid * 1e3, 1),
            "docs_per_s": round(32 / t_deid, 1),
        }
        log(
            f"config2 deid: batch-32 in {t_deid*1e3:.0f}ms = "
            f"{32/t_deid:.0f} docs/s"
        )
        del deid
        gc.collect()
        if not small:

            def run_deid_quality_late():
                # quality, not just speed: score the trained tagger on
                # the three-split evalset (deid/evalset.py).  "test" is
                # honestly a SECOND dev set — r5 tuned deny-words/cues
                # against its spans — so its F1 carries tuning optimism;
                # "heldout" (new in PR 7) was never scored during tuning
                # and is the generalization number.  BOTH are reported
                # so the optimism gap is itself a measured quantity.
                try:
                    from docqa_tpu.deid.evalset import evaluate_deid_split

                    t0 = time.perf_counter()
                    deid_trained = DeidEngine.trained(
                        NERConfig(), params_path=_ner_cache
                    )
                    ev = evaluate_deid_split(deid_trained)
                    DETAILS["deid"].update(
                        {
                            "train_s": round(time.perf_counter() - t0, 1),
                            "f1": ev["test"]["entity_f1"],
                            "f1_label": "second-dev (tuning optimism)",
                            "f1_heldout": ev["heldout"]["entity_f1"],
                            "f1_heldout_ci95": ev["heldout"][
                                "entity_f1_ci95"
                            ],
                            "char_f1": ev["test"]["char_f1"],
                            "char_f1_heldout": ev["heldout"]["char_f1"],
                            "span_recall_any": ev["test"]["span_recall_any"],
                            "span_recall_any_heldout": ev["heldout"][
                                "span_recall_any"
                            ],
                            "eval": ev,
                        }
                    )
                    log(f"config2 deid quality (dev/test/heldout): {ev}")
                    del deid_trained
                    gc.collect()
                except Exception as e:
                    log(f"deid quality eval failed: {e!r}")
                    DETAILS["deid"]["eval_error"] = repr(e)[:300]

            late_sections.append(("deid_quality", run_deid_quality_late, 420))

    run_section("deid", sec_deid, 120)

    # ---- IVF / tiered: recall@10 + latency vs exact -------------------------
    def sec_ivf():
        from docqa_tpu.engines.retrieve import FusedTieredRetriever
        from docqa_tpu.index.tiered import TieredIndex

        tiered = TieredIndex(
            store,
            # shipped default nprobe (frontier-tuned, docqa-meshindex):
            # the bench measures the configuration serving actually runs
            min_rows=10_000,
            rebuild_tail_rows=10 * n_chunks,  # no background churn mid-bench
            n_clusters=None if small else 1000,
        )
        t0 = time.perf_counter()
        tiered.rebuild()
        t_build = time.perf_counter() - t0
        probes = clustered_vectors(rng, 20, dim, centers)
        exact_res = store.search(probes, k=10)
        tiered.search(probes, k=10)  # compile at the TIMED batch shape
        t_tier, tier_res = timed(lambda: tiered.search(probes, k=10))
        hits = total = 0
        for e_row, a_row in zip(exact_res, tier_res):
            want = {r.row_id for r in e_row}
            hits += len(want & {r.row_id for r in a_row})
            total += len(want)
        t_exact20, _ = timed(lambda: store.search(probes, k=10))
        one = probes[:1]
        store.search(one, k=10)
        tiered.search(one, k=10)  # compile batch-1 shapes
        t_tier1, _ = timed(lambda: tiered.search(one, k=10), n=5)
        t_exact1, _ = timed(lambda: store.search(one, k=10), n=5)
        ft = FusedTieredRetriever(encoder, tiered)
        ft.search_texts([q_texts[0]], k=10)  # compile
        t_ftier, _ = timed(lambda: ft.search_texts([q_texts[1]], k=10), n=5)
        DETAILS["ivf"] = {
            "recall_at_10": round(hits / max(total, 1), 4),
            "build_s": round(t_build, 1),
            "tiered_batch20_ms": round(t_tier * 1e3, 2),
            "exact_batch20_ms": round(t_exact20 * 1e3, 2),
            "tiered_batch1_ms": round(t_tier1 * 1e3, 2),
            "exact_batch1_ms": round(t_exact1 * 1e3, 2),
            "fused_tiered_query_ms": round(t_ftier * 1e3, 2),
        }
        log(
            f"ivf: recall@10 {hits/max(total,1):.3f}, build {t_build:.1f}s, "
            f"batch-20 tiered {t_tier*1e3:.1f}ms vs exact "
            f"{t_exact20*1e3:.1f}ms; batch-1 tiered {t_tier1*1e3:.1f}ms "
            f"vs exact {t_exact1*1e3:.1f}ms"
        )
        # hand the built tier to sec_retrieval_quality (rebuilding a
        # 1M-row IVF just to measure its recall would double the cost)
        S["tiered"] = tiered
        del ft
        gc.collect()

    run_section("ivf", sec_ivf, 400 if not small else 90)

    # ---- retrieval quality: online recall, frontier, shadow overhead --------
    def sec_retrieval_quality():
        """docqa-recallscope measured on the bench corpus: the shadow
        estimator's online recall@10 + Wilson CI at the serving nprobe,
        the observed nprobe recall/latency frontier, and the
        shadow-sampling overhead A/B on the tiered qa_e2e path — same
        2% budget discipline as the trace/telemetry/dispatch overhead
        sections.  The OFF arm must show ZERO shadow dispatches (the
        acceptance bullet), counted at the spine stage."""
        from docqa_tpu import obs as _obs
        from docqa_tpu.engines.retrieve import FusedTieredRetriever
        from docqa_tpu.engines.spine import get_spine
        from docqa_tpu.index.tiered import TieredIndex

        tiered = S.pop("tiered", None)
        if tiered is None:  # sec_ivf skipped on budget: build our own
            tiered = TieredIndex(
                store, min_rows=10_000,
                rebuild_tail_rows=10 * n_chunks,
                n_clusters=None if small else 1000,
            )
            tiered.rebuild()
        ft = FusedTieredRetriever(encoder, tiered)

        def shadow_stage_count():
            row = get_spine().stats()["stages"].get("retrieve_shadow")
            return row["count"] if row else 0

        # -- phase 1: recall estimate + frontier (every retrieval
        # shadowed so the smoke-corpus estimate converges in seconds)
        robs = _obs.RetrievalObservatory(
            sample_every=1, seed=0, frontier_every=3, min_frontier_n=1,
            registry=_REG,
        ).start()
        _obs.set_retrieval_observatory(robs)
        try:
            probes = clustered_vectors(rng, 20, dim, centers)
            tiered.search(probes, k=10)  # compile at the measured shape
            for _ in range(12):
                tiered.search(probes, k=10)
            drained = robs.drain(180)
            st = robs.status()
        finally:
            _obs.set_retrieval_observatory(None)
            robs.stop()
        est = st["estimate"] or {}
        out = {
            "recall_estimate": est.get("recall"),
            "recall_ci": [est.get("ci_lo"), est.get("ci_hi")],
            "comparisons": est.get("comparisons"),
            "nprobe": (st["current"] or {}).get("nprobe"),
            "recall_target": st["recall_target"],
            "recommended_nprobe": st["recommended_nprobe"],
            "frontier": st["frontier"],
            "counts": st["counts"],
            "drained": drained,
        }

        # -- phase 2: overhead A/B on the tiered qa_e2e path, THREE
        # arms: off / the shipped default sampling rate (the arm the 2%
        # budget applies to) / worst-case 1-in-1 (every retrieval
        # shadowed — informative ceiling, not the shipped config).
        # Frontier probing off in both ON arms (a boot-class compile
        # cost, excluded like the telemetry A/B excludes the AOT HBM
        # probe).  The deterministic sampler fires exactly once per
        # sample_every retrievals (one hashed slot per window), so the
        # off and default arms run 2x the rate in requests — fewer
        # would measure an arm containing ZERO shadows and call the
        # jitter "overhead".
        if S["gen1"] is None:
            S["gen1"] = GenerateEngine(
                dataclasses.replace(dec_cfg, quantize_weights=True),
                mesh=mesh,
            )
        ask_tiered = make_ask(S["gen1"], retr=ft)
        for q in q_texts[:2]:  # compile at the measured shapes
            ask_tiered(q)
        n_ab = max(n_e2e, 8)

        def run_p50(n_req: int) -> float:
            lats = []
            for i in range(n_req):
                q = q_texts[2 + i % n_queries]
                t0 = time.perf_counter()
                ask_tiered(q)
                lats.append((time.perf_counter() - t0) * 1e3)
            return float(np.percentile(lats, 50))

        from docqa_tpu.config import RetrievalQualityConfig

        default_rate = RetrievalQualityConfig().sample_every
        n_def = 2 * default_rate  # exactly 2 sampled shadows per arm
        off0 = shadow_stage_count()
        p50_off = run_p50(n_def)
        off_shadow = shadow_stage_count() - off0

        def run_sampled(sample_every: int, n_req: int) -> Tuple[float, int]:
            robs2 = _obs.RetrievalObservatory(
                sample_every=sample_every, frontier_every=0,
                registry=_REG,
            ).start()
            _obs.set_retrieval_observatory(robs2)
            try:
                p50 = run_p50(n_req)
                robs2.drain(60)
                sampled = robs2.status()["counts"]["sampled"]
            finally:
                _obs.set_retrieval_observatory(None)
                robs2.stop()
            return p50, sampled

        p50_def, def_sampled = run_sampled(default_rate, n_def)
        p50_all, _ = run_sampled(1, n_ab)
        overhead_def = (
            (p50_def - p50_off) / p50_off * 100.0 if p50_off else 0.0
        )
        overhead_all = (
            (p50_all - p50_off) / p50_off * 100.0 if p50_off else 0.0
        )
        out["overhead"] = {
            "qa_e2e_p50_off_ms": round(p50_off, 2),
            "qa_e2e_p50_default_ms": round(p50_def, 2),
            "qa_e2e_p50_worstcase_ms": round(p50_all, 2),
            # the shipped config (1-in-N sampling) is what the 2% budget
            # governs; the 1-in-1 ceiling is reported beside it so the
            # amortization claim stays checkable
            "overhead_pct": round(overhead_def, 2),
            "overhead_worstcase_pct": round(overhead_all, 2),
            "sampling_default": f"1-in-{default_rate}",
            "samples_off_and_default": n_def,
            "default_arm_shadows_sampled": def_sampled,
            "samples_worstcase": n_ab,
            "budget_pct": 2.0,
            "within_budget": overhead_def <= 2.0,
            # MUST be zero: sampling disabled == zero shadow dispatches
            "off_arm_shadow_dispatches": off_shadow,
        }
        if off_shadow:
            log(
                f"RETRIEVAL QUALITY VIOLATION: {off_shadow} shadow "
                "dispatches with sampling disabled (must be 0)"
            )
        DETAILS["retrieval_quality"] = out
        # honesty column (the rag_load fix): every section quoting
        # tiered latency now carries the measured recall beside it
        recall_col = {
            "recall_estimate": out["recall_estimate"],
            "recall_ci": out["recall_ci"],
            "nprobe": out["nprobe"],
            "source": "retrieval_quality (online shadow estimator)",
        }
        for key in ("ivf", "rag_load", "rag_load_7b_int8"):
            sec = DETAILS.get(key)
            if isinstance(sec, dict):
                sec["retrieval_recall"] = recall_col
        log(
            f"retrieval_quality: recall@10 {out['recall_estimate']} "
            f"CI {out['recall_ci']} at nprobe {out['nprobe']} "
            f"(target {out['recall_target']}, recommended "
            f"{out['recommended_nprobe']}); shadow overhead "
            f"{overhead_def:+.2f}% at 1-in-{default_rate} (budget 2%; "
            f"1-in-1 ceiling {overhead_all:+.2f}%), off-arm shadow "
            f"dispatches {off_shadow}"
        )
        del ft, tiered
        gc.collect()

    run_section("retrieval_quality", sec_retrieval_quality,
                420 if not small else 90)
    # if the section was budget-SKIPPED, the tier sec_ivf parked in S
    # must still be freed here — pinning 1M-row cell tensors through the
    # HBM-hungry 7B/int4 sections would shift their numbers
    S.pop("tiered", None)
    gc.collect()

    # ---- answer routing (docqa-lexroute) ------------------------------------
    def sec_answer_routing():
        """The confidence-gated decoder-skip router measured end to end:
        per-route p50 on the checked-in labeled EN+FR mix (the ~600ms ->
        ~50ms split shape) and hybrid-vs-dense evidence recall with
        Wilson CIs on the mix's 20 lookups.  The recall A/B is the PR 13
        decision evidence for the serving default: hybrid stays ADVISORY
        (``lexical.serving_mode`` ships dense) unless its CI-low beats
        dense CI-high on representative traffic — this mix is
        lookup-shaped BY CONSTRUCTION, so the section reports the
        recommendation, it does not flip the default."""
        from docqa_tpu.engines.router import AnswerRouter
        from docqa_tpu.index.lexical import LexicalIndex
        from docqa_tpu.index.tiered import TieredIndex
        from docqa_tpu.obs.retrieval_observatory import wilson_interval
        from docqa_tpu.service.qa import QAService

        mix_path = os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            "data", "routing_mix.jsonl",
        )
        with open(mix_path, encoding="utf-8") as f:
            mix = [json.loads(ln) for ln in f if ln.strip()]
        ev = [ex for ex in mix if "doc" in ex]

        # routing corpus: the mix's evidence docs among filler chunks
        # from the bench pool, in a dedicated store so the 1M-row bench
        # corpus (no lexical sink registered at ingest) stays untouched
        filler = pool_texts[: 512 if small else 2048]
        texts = list(filler) + [ex["doc"] for ex in ev]
        lex = LexicalIndex(mesh=mesh)
        store_r = VectorStore(
            StoreConfig(dim=dim, shard_capacity=8192), mesh=mesh
        )
        store_r.register_index_sink(lex)
        embs = np.concatenate(
            [
                encoder.encode_texts(texts[i : i + 64])
                for i in range(0, len(texts), 64)
            ]
        )
        store_r.add(
            embs,
            [
                {"doc_id": f"rf{i}", "source": f"filler {i}",
                 "text_content": t}
                for i, t in enumerate(filler)
            ]
            + [
                {"doc_id": ex["id"], "source": f"mix/{ex['id']}",
                 "text_content": ex["doc"]}
                for ex in ev
            ],
        )
        gt_row = {ex["id"]: len(filler) + i for i, ex in enumerate(ev)}
        tiered_r = TieredIndex(
            store_r, min_rows=10**9, rebuild_tail_rows=10**9,
            lexical=lex,
        )

        # hybrid-vs-dense evidence recall: hit = the labeled evidence
        # doc's row in the top-k, Wilson CI over the 20 lookups
        k_r = 5
        qs = [ex["question"] for ex in ev]
        q_emb = np.concatenate(
            [encoder.encode_texts(qs[i : i + 64])
             for i in range(0, len(qs), 64)]
        )
        recall_ab = {}
        for m in ("dense", "hybrid"):
            got = tiered_r.search(q_emb, k=k_r, mode=m, query_texts=qs)
            n_hit = sum(
                any(r.row_id == gt_row[ex["id"]] for r in row)
                for ex, row in zip(ev, got)
            )
            lo, hi = wilson_interval(n_hit, len(ev))
            recall_ab[m] = {
                "hits": n_hit, "n": len(ev),
                "recall": round(n_hit / len(ev), 3),
                "ci_lo": round(lo, 4), "ci_hi": round(hi, 4),
            }
        hybrid_wins = (
            recall_ab["hybrid"]["ci_lo"] > recall_ab["dense"]["ci_hi"]
        )

        # per-route p50: the mix through a routed QAService on the real
        # decode engine — routed-extractive answers skip the decoder
        if S["gen1"] is None:
            S["gen1"] = GenerateEngine(
                dataclasses.replace(dec_cfg, quantize_weights=True),
                mesh=mesh,
            )
        qa = QAService(
            encoder, tiered_r, S["gen1"], None, k=k_r,
            router=AnswerRouter(),
        )
        qa.ask("Summarize the admission note.")  # compile generative arm
        qa.ask(ev[0]["question"])  # compile the hybrid retrieve arm
        lats = {"extractive": [], "generative": []}
        tp = fp = 0
        for ex in mix:
            t0 = time.perf_counter()
            out = qa.ask(ex["question"])
            lat_ms = (time.perf_counter() - t0) * 1e3
            routed = (
                "extractive" if out.get("route") == "extractive"
                else "generative"
            )
            lats[routed].append(lat_ms)
            if routed == "extractive":
                if ex["label"] == "extractive":
                    tp += 1
                else:
                    fp += 1
        p50 = {
            r: (round(float(np.percentile(xs, 50)), 1) if xs else None)
            for r, xs in lats.items()
        }
        precision = tp / max(tp + fp, 1)
        DETAILS["answer_routing"] = {
            "mix": os.path.relpath(mix_path, os.path.dirname(
                os.path.abspath(__file__))),
            "n_requests": len(mix),
            "routed_extractive": len(lats["extractive"]),
            "routed_generative": len(lats["generative"]),
            "routing_precision": round(precision, 3),
            "p50_ms": p50,
            "split_ratio": (
                round(p50["generative"] / p50["extractive"], 1)
                if p50["extractive"] and p50["generative"] else None
            ),
            "evidence_recall": recall_ab,
            "hybrid_ci_low_beats_dense": hybrid_wins,
            "serving_default": "dense (hybrid advisory: the mix is "
            "lookup-shaped by construction, not representative traffic)",
        }
        log(
            f"answer_routing: precision {precision:.3f} "
            f"({len(lats['extractive'])}/{len(mix)} routed extractive); "
            f"p50 extractive {p50['extractive']}ms vs generative "
            f"{p50['generative']}ms; evidence recall dense "
            f"{recall_ab['dense']['recall']} "
            f"[{recall_ab['dense']['ci_lo']}, "
            f"{recall_ab['dense']['ci_hi']}] vs hybrid "
            f"{recall_ab['hybrid']['recall']} "
            f"[{recall_ab['hybrid']['ci_lo']}, "
            f"{recall_ab['hybrid']['ci_hi']}] "
            f"(hybrid CI-low beats dense: {hybrid_wins})"
        )
        del qa, tiered_r, store_r, lex
        gc.collect()

    run_section("answer_routing", sec_answer_routing,
                240 if not small else 90)

    # ---- IVF crossover at 2M/4M rows (VERDICT r4 item 4) --------------------
    # Vectors only (no sidecar), measured in the regime the bytes model
    # says IVF should win.  Slow (ingest + build per scale) — runs only
    # with a raised budget (in-session / DOCQA_BENCH_BUDGET_S override).
    def sec_ivf_scale():
        from docqa_tpu.index.tiered import TieredIndex

        S["gen1"] = None
        gc.collect()
        out = {}
        for target_n in (2_000_000, 4_000_000):
            if remaining() < 900:
                out[str(target_n)] = "skipped: budget"
                break
            big = VectorStore(
                StoreConfig(shard_capacity=target_n), mesh=mesh
            )
            rngb = np.random.default_rng(1)
            t0 = time.perf_counter()
            for start in range(0, target_n, block):
                n = min(block, target_n - start)
                big.add(
                    clustered_vectors(rngb, n, dim, centers),
                    [{"doc_id": f"s{i}"} for i in range(start, start + n)],
                )
                DETAILS["ivf_scale_ingest"] = f"{target_n}:{start + n}"
            t_ing = time.perf_counter() - t0
            # clusters capped: the full-corpus assignment pass scales with
            # n x C, and the crossover question is about SEARCH latency,
            # not k-means asymptotics — C=2000 at 4M keeps the build in
            # minutes while a 32-probe still scans ~5% of the corpus
            tiered = TieredIndex(
                big,
                min_rows=10_000,
                rebuild_tail_rows=10 * target_n,
                n_clusters=min(2000, int(np.sqrt(target_n))),
            )
            t0 = time.perf_counter()
            tiered.rebuild()
            t_build = time.perf_counter() - t0
            probes = clustered_vectors(rngb, 20, dim, centers)
            exact_res = big.search(probes, k=10)
            tiered.search(probes, k=10)
            t_t20, tier_res = timed(lambda: tiered.search(probes, k=10), n=3)
            t_e20, _ = timed(lambda: big.search(probes, k=10), n=3)
            one = probes[:1]
            big.search(one, k=10)
            tiered.search(one, k=10)
            t_t1, _ = timed(lambda: tiered.search(one, k=10), n=5)
            t_e1, _ = timed(lambda: big.search(one, k=10), n=5)
            hits = total = 0
            for e_row, a_row in zip(exact_res, tier_res):
                want = {r.row_id for r in e_row}
                hits += len(want & {r.row_id for r in a_row})
                total += len(want)
            out[str(target_n)] = {
                "ingest_s": round(t_ing, 1),
                "build_s": round(t_build, 1),
                "recall_at_10": round(hits / max(total, 1), 4),
                "tiered_batch1_ms": round(t_t1 * 1e3, 2),
                "exact_batch1_ms": round(t_e1 * 1e3, 2),
                "tiered_batch20_ms": round(t_t20 * 1e3, 2),
                "exact_batch20_ms": round(t_e20 * 1e3, 2),
            }
            log(f"ivf_scale {target_n}: {out[str(target_n)]}")
            DETAILS["ivf_scale"] = out
            flush_details()
            del tiered, big
            gc.collect()
        DETAILS["ivf_scale"] = out

    if not small:
        run_section("ivf_scale", sec_ivf_scale, 1200)

    # ---- mesh-sharded int8 tier: 1M→10M crossover + frontier ---------------
    # (docqa-meshindex, ROADMAP item 2's "done" evidence).  Slow — runs
    # only with a raised budget; scripts/shard_scale_bench.py runs the
    # same sweep standalone and merges into bench_details.json.
    def sec_shard_scale():
        S["gen1"] = None
        gc.collect()
        DETAILS["shard_scale"] = run_shard_scale(
            mesh=mesh, budget_s=max(remaining() - 180, 120), on_tpu=on_tpu,
        )

    if not small:
        run_section("shard_scale", sec_shard_scale, 1500)

    # ---- config 3d: 7B grouped-int4 (w4a16) ---------------------------------
    def sec_int4():
        import jax.numpy as _jnp

        from docqa_tpu.models.quant import (
            init_quantized_decoder_params,
            probe_int4_support,
        )

        S["gen1"] = None
        gc.collect()
        # Capability gate FIRST (r04 post-mortem): an ungated S4 compile
        # on the tunneled backend poisoned every later dispatch.  The toy
        # probe fails fast WITHOUT poisoning the client.
        _int4_ok, _int4_why = probe_int4_support()
        if not _int4_ok:
            raise RuntimeError(
                f"backend cannot execute int4 programs (probe: {_int4_why})"
            )
        try:
            from docqa_tpu.models.decoder import _qmatmul

            _g = 128
            _probe_p = {
                "w": _jnp.zeros(
                    (cfg7.mlp_dim // _g, _g, cfg7.hidden_dim), _jnp.int4
                ),
                "w__scale": _jnp.zeros(
                    (cfg7.mlp_dim // _g, cfg7.hidden_dim), _jnp.float32
                ),
            }
            _x = _jnp.zeros((1, cfg7.mlp_dim), _jnp.bfloat16)
            _ma = (
                jax.jit(lambda x, p: _qmatmul(x, p, "w", _jnp.bfloat16))
                .lower(_x, _probe_p)
                .compile()
                .memory_analysis()
            )
            DETAILS["int4_fusion_probe"] = {
                "temp_bytes": int(_ma.temp_size_in_bytes),
                "materialized_tree_bytes": cfg7.mlp_dim * cfg7.hidden_dim * 2,
            }
            log(f"int4 fusion probe: {DETAILS['int4_fusion_probe']}")
            del _probe_p, _x
        except Exception as e:
            log(f"int4 fusion probe failed: {e!r}")
        params4 = init_quantized_decoder_params(
            jax.random.PRNGKey(0), cfg7, host_init=True, bits=4, host_seed=0
        )
        try:
            pb4 = param_bytes(params4)  # host itemsize counts int4 as 1B
            gen4 = GenerateEngine(
                cfg7,
                GenerateConfig(max_new_tokens=64, prefill_buckets=(512,)),
                params=params4,
            )
            gen4.generate_ids([[5, 9, 11]], max_new_tokens=64)  # compile
            t4, _ = timed(
                lambda: gen4.generate_ids([[5, 9, 11]], max_new_tokens=64),
                n=3,
            )
            tok4 = 64 / t4
            pb4_packed = pb4 - sum(
                int(np.prod(v.shape)) // 2
                for v in params4.values()
                if str(v.dtype) == "int4"
            )
            util4 = tok4 * pb4_packed / (V5E_HBM_GBPS * 1e9)
            DETAILS["decode_7b_int4"] = {
                "tokens_per_s": round(tok4, 1),
                "param_bytes_gb": round(pb4_packed / 1e9, 2),
                "hbm_utilization": round(util4, 3),
                "hbm_utilization_basis": (
                    "measured-on-v5e" if on_tpu
                    else "projected-v5e (CPU run)"
                ),
            }
            log(
                f"config3d 7B int4 ({pb4_packed/1e9:.1f}GB packed): "
                f"{tok4:.1f} tok/s, HBM util {util4:.0%}"
            )
            p50_4, p95_4 = measure_e2e(
                gen4, q_texts[2 : 2 + n_e2e], "7B-int4 spec_k=0"
            )
            DETAILS["qa_e2e_7b_int4"] = {
                "p50_ms": round(p50_4, 2),
                "p95_ms": round(p95_4, 2),
                "new_tokens": max_new,
                "decoder": "mistral-7b-class-int4-g128",
            }
            del gen4
        finally:
            del params4
            gc.collect()

    if not small:
        run_section("int4_7b", sec_int4, 300)

    # ---- config 3b: the same 7B in bf16 (14.5 GB) — needs ALL the HBM -------
    def sec_bf16_7b():
        import jax.numpy as jnp

        from docqa_tpu.models.decoder import init_decoder_params

        S["gen1"] = None
        gc.collect()
        # device-side init deliberately: host init would draw + transfer
        # 14.5 GB through the tunnel (minutes) and nothing latency-
        # sensitive is measured after this section
        params7 = init_decoder_params(
            jax.random.PRNGKey(0), cfg7, param_dtype=jnp.bfloat16
        )
        try:
            pb7 = param_bytes(params7)
            gen7 = GenerateEngine(
                cfg7,
                GenerateConfig(max_new_tokens=64, prefill_buckets=(128,)),
                params=params7,
            )
            gen7.generate_ids([[5, 9, 11]], max_new_tokens=64)  # compile
            t7, _ = timed(
                lambda: gen7.generate_ids([[5, 9, 11]], max_new_tokens=64),
                n=3,
            )
            tok7 = 64 / t7
            util7 = tok7 * pb7 / (V5E_HBM_GBPS * 1e9)
            DETAILS["decode_7b"] = {
                "tokens_per_s": round(tok7, 1),
                "param_bytes_gb": round(pb7 / 1e9, 2),
                "hbm_utilization": round(util7, 3),
                "hbm_utilization_basis": (
                    "measured-on-v5e" if on_tpu
                    else "projected-v5e (CPU run)"
                ),
            }
            log(
                f"config3b 7B bf16 ({pb7/1e9:.1f}GB): {tok7:.0f} tok/s, "
                f"HBM util {util7:.0%}"
            )
            del gen7
        finally:
            del params7
            gc.collect()

    if not small:
        if remaining() >= 240:
            # one v5e chip has 16 GB HBM; the 14.5 GB tree needs the
            # store/encoder gone first (rebinding clears the closure
            # cells — every section that used them has already run)
            retriever = None
            store = None
            encoder = None
            gc.collect()
            run_section("bf16_7b", sec_bf16_7b, 240)
        else:
            DETAILS.setdefault("skipped", {})["bf16_7b"] = (
                f"budget: {remaining():.0f}s left, need ~240s"
            )
            log("SKIP bf16_7b: budget")

    # ---- late sections (slow compiles / training) ---------------------------
    for name, fn, need in late_sections:
        run_section(name, fn, need)

    _bench_sampler.stop()
    DETAILS["telemetry_snapshot"] = _bench_tstore.snapshot()
    DETAILS["total_wall_s"] = round(time.monotonic() - T0, 1)
    flush_details()
    # the log line stays human-readable: the full time-series snapshot
    # lives in bench_details.json only
    log(
        "details: "
        + json.dumps(
            {k: v for k, v in DETAILS.items() if k != "telemetry_snapshot"}
        )
    )


if __name__ == "__main__":
    if os.environ.get("DOCQA_BENCH_INNER") == "1":
        main()
    else:
        sys.exit(_run_with_fallback())
